"""int8×int8→int32 matmuls on quantized weights — the int8 MXU path.

The v5e (and every TPU since v4i) runs int8×int8 contractions at ~2× the
bf16 MXU rate. The weight-only quantization in `utils/quantization.py`
(the bitsandbytes analog, reference `utils/bnb.py:44`) stores int8 weights
but dequantizes to bf16 before every matmul — fine for bandwidth-bound
B=1 decode, where HBM bytes are the roofline, but prefill and speculative
VERIFY are compute-bound: dequantizing first leaves the 2× int8 MXU rate
on the table.

This module closes that gap with the fp8 module's recipe at int8 dtypes:

- activations are dynamically quantized per tensor (symmetric,
  ``amax/127`` — one fp32 scale, no calibration state);
- the contraction runs on int8 values with int32 accumulation
  (``preferred_element_type``), which XLA lowers onto the int8 MXU;
- the int32 result is rescaled by ``act_scale × weight_scale`` where the
  weight scales are the per-output-channel scales the quantized pytree
  already carries — so the WEIGHT quantization error is identical to the
  dequantize-first path and only the activation rounding is new.

Enablement mirrors `fp8_matmuls`: inside an :func:`int8_compute` context
(read at trace time), `ops.fp8.matmul_einsum` routes quantized-dict
weights through :func:`int8_einsum_quantized` instead of dequantizing.
Packed int4 weights unpack to int8 values first (elementwise) and then
take the same int8 MXU contraction.

Inference-only by design: the backward of an int8 contraction would need
requantized gradients; training stays on the bf16/fp8 paths.
"""

from __future__ import annotations

import contextlib
import functools
import threading

import jax
import jax.numpy as jnp

_MODE = threading.local()


def int8_compute_enabled() -> bool:
    return getattr(_MODE, "int8", False)


@contextlib.contextmanager
def int8_compute(enabled: bool = True):
    """While active (including during jit tracing), `matmul_einsum` runs
    quantized-weight contractions on the int8 MXU instead of dequantizing
    to the compute dtype first.

    CAVEAT (jit cache): the mode is read at TRACE time, and jax shares the
    trace cache across ``jax.jit`` wrappers of the SAME function object —
    ``jax.jit(f)`` traced outside the context and ``jax.jit(f)`` called
    inside it silently reuse one jaxpr. To jit a function per-mode, wrap it
    with :func:`with_int8_compute` (a fresh function object whose every
    trace happens inside the context)."""
    prev = getattr(_MODE, "int8", False)
    _MODE.int8 = enabled
    try:
        yield
    finally:
        _MODE.int8 = prev


def with_int8_compute(fn):
    """Return a NEW callable that always executes (and therefore always
    TRACES) ``fn`` inside :func:`int8_compute` — the safe way to build an
    int8-mode jit next to a normal-mode jit of the same function:

        f_bf16 = jax.jit(fwd)
        f_int8 = jax.jit(with_int8_compute(fwd))

    The fresh function object gives the int8 variant its own jit cache
    entry, so it can never alias the bf16 trace."""

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        with int8_compute():
            return fn(*args, **kwargs)

    return wrapped


def quantize_act(
    x: jax.Array, reduce_axes: tuple[int, ...] | None = None
) -> tuple[jax.Array, jax.Array]:
    """Dynamic int8 scaling: ``(q, scale)`` with ``q ≈ x/scale`` in int8 and
    ``scale = amax/127`` (fp32). ``reduce_axes=None`` gives one per-tensor
    scalar; a tuple gives PER-ROW scales (amax over the contracted axes,
    keepdims) — one scale per token, which cuts the activation-rounding
    drift that per-tensor scaling accumulates with depth (outlier tokens no
    longer squash everyone else's range)."""
    xf = x.astype(jnp.float32)
    if reduce_axes is None:
        amax = jnp.max(jnp.abs(xf))
    else:
        amax = jnp.max(jnp.abs(xf), axis=reduce_axes, keepdims=True)
    scale = jnp.maximum(amax, 1e-12) / 127.0
    q = jnp.clip(jnp.round(xf / scale), -127, 127).astype(jnp.int8)
    return q, scale


def _w_scale_to_out(eq: str, w_scale: jax.Array) -> jax.Array:
    """Align a per-output-channel weight scale (w's shape with contracted
    dims kept as size 1) to the OUTPUT of ``einsum(eq, x, w)``.

    Contracted axes of ``w_scale`` are size 1 (the quantizer reduces over
    them with keepdims), so summing them away via einsum is the identity;
    output labels w doesn't carry broadcast as size-1 dims."""
    ins, out = eq.split("->")
    _, b = ins.split(",")
    kept = "".join(lbl for lbl in out if lbl in b)
    squeezed = jnp.einsum(f"{b}->{kept}", w_scale.astype(jnp.float32))
    shape = tuple(
        squeezed.shape[kept.index(lbl)] if lbl in kept else 1 for lbl in out
    )
    return squeezed.reshape(shape)


def _unpack_int4(packed: jax.Array) -> jax.Array:
    """Packed uint8 (two 4-bit values per byte, `utils/quantization.py`
    layout) -> int8 values in [-7, 7], doubling the last axis."""
    hi = (packed >> 4).astype(jnp.int8) - 8
    lo = (packed & 0xF).astype(jnp.int8) - 8
    return jnp.stack([hi, lo], axis=-1).reshape(
        packed.shape[:-1] + (packed.shape[-1] * 2,)
    )


def _x_contracted_axes(eq: str) -> tuple[int, ...]:
    """Axes of x reduced by ``einsum(eq, x, w)`` (labels shared with w and
    absent from the output) — the per-row quantization group."""
    ins, out = eq.split("->")
    a, b = ins.split(",")
    return tuple(i for i, lbl in enumerate(a) if lbl in b and lbl not in out)


def _x_scale_to_out(eq: str, x_scale: jax.Array) -> jax.Array:
    """Align a per-row activation scale (x's shape with contracted dims kept
    as size 1) to the output of ``einsum(eq, x, w)`` — the x-side twin of
    `_w_scale_to_out`."""
    ins, out = eq.split("->")
    a, _ = ins.split(",")
    kept = "".join(lbl for lbl in out if lbl in a)
    squeezed = jnp.einsum(f"{a}->{kept}", x_scale.astype(jnp.float32))
    shape = tuple(
        squeezed.shape[kept.index(lbl)] if lbl in kept else 1 for lbl in out
    )
    return squeezed.reshape(shape)


def int8_einsum(
    eq: str, x: jax.Array, wq: jax.Array, w_scale: jax.Array
) -> jax.Array:
    """``einsum(eq, x, dequant(wq))`` computed as int8×int8→int32 on the
    MXU: dynamic per-token activation quantization, int32 accumulation,
    exact rescale by ``per-row act scale × per-channel weight scale``.

    When the `int8_matmul` Pallas kernel is enabled (`native/pallas/`),
    the quantize -> dot -> rescale runs as one fused kernel — integer
    accumulation exact, parity within 1 ulp of the activation scale —
    without the intermediate HBM round-trips."""
    try:
        from ..native.pallas.quant_matmul import maybe_int8_matmul
    except Exception:  # pragma: no cover - environment dependent
        maybe_int8_matmul = None
    if maybe_int8_matmul is not None:
        out = maybe_int8_matmul(eq, x, wq, w_scale)
        if out is not None:
            return out
    qx, sx = quantize_act(x, _x_contracted_axes(eq))
    acc = jnp.einsum(eq, qx, wq, preferred_element_type=jnp.int32)
    scale = _x_scale_to_out(eq, sx) * _w_scale_to_out(eq, w_scale)
    return (acc.astype(jnp.float32) * scale).astype(x.dtype)


def int8_einsum_quantized(eq: str, x: jax.Array, wnode: dict) -> jax.Array:
    """`int8_einsum` over a ``{"__quant__"|"__quant4__", "scale"}`` node
    from `utils/quantization.py` (int4 unpacks to int8 values first —
    same MXU path, half the HBM bytes)."""
    from ..utils.quantization import _QUANT4_KEY, _QUANT_KEY

    if _QUANT4_KEY in wnode:
        return int8_einsum(eq, x, _unpack_int4(wnode[_QUANT4_KEY]), wnode["scale"])
    return int8_einsum(eq, x, wnode[_QUANT_KEY], wnode["scale"])

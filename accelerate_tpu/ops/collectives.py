"""Pytree collectives & data-movement operations.

Analog of the reference `utils/operations.py` (867 LoC): pytree-recursive
gather/broadcast/reduce/pad, host-object collectives, device placement, dtype
conversion, and the debug-mode cross-process shape check
(`verify_operation`, reference `utils/operations.py:355-417`).

Two regimes, cleanly separated:

1. **Host-level** (this module): operates on process-local numpy/JAX arrays or
   already-global sharded `jax.Array`s, *outside* jit. Multi-host transport is
   the JAX runtime (`multihost_utils`) — the analog of the reference's
   `torch.distributed.all_gather`/`broadcast_object_list` calls.
2. **In-jit** (`ops/in_jit.py` re-exports): `lax.psum`/`all_gather`/`ppermute`
   inside `shard_map`-ped compiled code — the reference has no equivalent; its
   collectives always run eagerly from Python.

The reference's `recursively_apply` (`operations.py:84`) is `jax.tree.map`.
"""

from __future__ import annotations

import os
import pickle
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..state import ProcessState
from ..utils.environment import parse_flag_from_env


class DistributedOperationException(Exception):
    """Raised when a collective would be called with mismatched inputs across
    processes (reference `operations.py:355`)."""


def _is_jax_array(x: Any) -> bool:
    return isinstance(x, jax.Array)


def _is_arraylike(x: Any) -> bool:
    return isinstance(x, (jax.Array, np.ndarray)) or np.isscalar(x)


def is_tensor_tree(tree: Any) -> bool:
    leaves = jax.tree.leaves(tree)
    return len(leaves) > 0 and all(_is_arraylike(leaf) for leaf in leaves)


def _maybe_collective_log(kind: str, name: str, tree: Any = None) -> None:
    """Opt-in runtime mirror of the ATX5xx simulated collective log
    (``ATX_COLLECTIVE_LOG=1``): records (kind, name, signature) at the REAL
    call site so multi-process tests can assert group agreement. One env
    lookup when off; never raises."""
    if os.environ.get("ATX_COLLECTIVE_LOG", "").strip().lower() not in (
        "1",
        "true",
        "yes",
        "on",
    ):
        return
    try:
        from ..analysis.collective_log import runtime_record

        runtime_record(
            kind, name, _tree_signature(tree) if tree is not None else ""
        )
    except Exception:  # pragma: no cover - diagnostics must not break steps
        pass


# --------------------------------------------------------------------- debug
def _tree_signature(tree: Any) -> str:
    def leaf_sig(x: Any) -> str:
        if hasattr(x, "shape") and hasattr(x, "dtype"):
            return f"{tuple(x.shape)}:{x.dtype}"
        return type(x).__name__

    structure = jax.tree.structure(tree)
    leaves = [leaf_sig(leaf) for leaf in jax.tree.leaves(tree)]
    return f"{structure}|{leaves}"


def verify_operation(name: str, tree: Any) -> None:
    """Debug-mode agreement check: all processes must pass identically
    structured/shaped pytrees to a collective. Enabled via ``ATX_DEBUG_MODE=1``
    (reference ``ACCELERATE_DEBUG_MODE``, `operations.py:355-417`)."""
    state = ProcessState()
    if not state.debug or state.num_processes == 1:
        return
    sig = _tree_signature(tree)
    sigs = gather_object([sig])
    if len(set(sigs)) > 1:
        raise DistributedOperationException(
            f"Mismatch in inputs to collective `{name}` across processes:\n"
            + "\n".join(f"  process {i}: {s}" for i, s in enumerate(sigs))
        )


# ------------------------------------------------------------------ movement
def send_to_device(tree: Any, sharding: NamedSharding | jax.Device | None = None) -> Any:
    """Place a pytree on device(s) (reference `send_to_device`,
    `operations.py:135`). With a `NamedSharding`, forms global sharded arrays;
    with a device or None, plain transfer."""
    if sharding is None:
        sharding = jax.devices()[0]
    return jax.tree.map(lambda x: jax.device_put(x, sharding), tree)


def to_host(tree: Any) -> Any:
    """Fetch a pytree of (possibly sharded but fully-addressable) arrays to
    host numpy."""
    return jax.tree.map(lambda x: np.asarray(x) if _is_jax_array(x) else x, tree)


def convert_to_fp32(tree: Any) -> Any:
    """Upcast all half-precision leaves to float32 (reference
    `convert_to_fp32`, `operations.py:765`)."""

    def _convert(x: Any) -> Any:
        if hasattr(x, "dtype") and x.dtype in (jnp.float16, jnp.bfloat16):
            return x.astype(jnp.float32)
        return x

    return jax.tree.map(_convert, tree)


def find_batch_size(tree: Any) -> int:
    """First leaf's leading dimension (reference `find_batch_size`,
    `operations.py:242`)."""
    for leaf in jax.tree.leaves(tree):
        if hasattr(leaf, "shape") and len(leaf.shape) > 0:
            return int(leaf.shape[0])
    raise ValueError("Cannot find the batch size from an empty pytree.")


def slice_tensors(tree: Any, tensor_slice: slice) -> Any:
    """Slice every leaf along dim 0 (reference `operations.py:581`)."""
    return jax.tree.map(
        lambda x: x[tensor_slice] if hasattr(x, "shape") and len(x.shape) else x, tree
    )


def concatenate(trees: Sequence[Any], dim: int = 0) -> Any:
    """Concatenate a list of same-structure pytrees leafwise (reference
    `operations.py:601`)."""
    return jax.tree.map(lambda *xs: np.concatenate(xs, axis=dim), *trees)


def get_data_structure(tree: Any) -> Any:
    """Shape/dtype skeleton of a pytree (reference `get_data_structure`,
    `operations.py:232`), as `jax.ShapeDtypeStruct`s."""
    return jax.tree.map(lambda x: jax.ShapeDtypeStruct(np.shape(x), np.asarray(x).dtype), tree)


def initialize_tensors(structure: Any) -> Any:
    """Materialize zeros matching a `get_data_structure` skeleton (reference
    `operations.py:219`)."""
    return jax.tree.map(lambda s: np.zeros(s.shape, s.dtype), structure)


# ---------------------------------------------------------------- collectives
def _process_allgather(x: Any, tiled: bool) -> np.ndarray:
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(x, tiled=tiled))


def gather(tree: Any) -> Any:
    """All-gather a pytree across the data-parallel world; returns host numpy.

    Reference `gather` (`operations.py:419`): every rank's `[B, ...]` tensor
    becomes `[B * world, ...]` on all ranks. Here there are two cases:

    - A *global* sharded `jax.Array` (the output of a jitted SPMD step)
      already **is** the concatenation; gather materializes it to host,
      all-gathering across hosts if shards are remote.
    - A *process-local* value (numpy or single-device array) is concatenated
      across processes along dim 0.
    """
    _maybe_collective_log("gather", "gather", tree)
    verify_operation("gather", tree)
    state = ProcessState()

    def _gather_leaf(x: Any) -> Any:
        if _is_jax_array(x) and getattr(x, "is_fully_addressable", True):
            if state.num_processes == 1:
                return np.asarray(x)
            return _process_allgather(np.asarray(x), tiled=True)
        if _is_jax_array(x):
            # Global array with remote shards: replicate via the runtime.
            from jax.experimental import multihost_utils

            return np.asarray(multihost_utils.process_allgather(x, tiled=True))
        if state.num_processes == 1:
            return np.asarray(x)
        return _process_allgather(np.asarray(x), tiled=True)

    return jax.tree.map(_gather_leaf, tree)


def reduce(tree: Any, reduction: str = "mean") -> Any:
    """Sum/mean a pytree across processes (reference `reduce`,
    `operations.py:724`). ``reduction`` in {"sum", "mean", "none"}."""
    if reduction == "none":
        return tree
    _maybe_collective_log("reduce", f"reduce[{reduction}]", tree)
    verify_operation("reduce", tree)
    state = ProcessState()

    def _reduce_leaf(x: Any) -> np.ndarray:
        arr = np.asarray(x)
        if state.num_processes == 1:
            return arr.copy()
        stacked = _process_allgather(arr, tiled=False)
        out = stacked.sum(axis=0)
        if reduction == "mean":
            out = out / state.num_processes
        return out.astype(arr.dtype)

    return jax.tree.map(_reduce_leaf, tree)


def broadcast(tree: Any, from_process: int = 0) -> Any:
    """Broadcast a pytree of arrays from one process to all (reference
    `broadcast`, `operations.py:539`).

    Contract (same as the reference/torch): EVERY process passes a tree of
    identical structure, shapes, and dtypes — non-source values are shape
    templates (`ATX_DEBUG_MODE=1` verifies agreement). For source-only
    payloads of arbitrary shape use `broadcast_object_list`.
    """
    _maybe_collective_log("broadcast", f"broadcast[from={from_process}]", tree)
    verify_operation("broadcast", tree)
    state = ProcessState()
    if state.num_processes == 1:
        return tree
    from jax.experimental import multihost_utils

    # True one-to-all (O(payload) per link, not the O(world) all-gather this
    # once was): any root via is_source.
    is_source = state.process_index == from_process
    return jax.tree.map(
        lambda x: np.asarray(
            multihost_utils.broadcast_one_to_all(np.asarray(x), is_source=is_source)
        ),
        tree,
    )


def pad_across_processes(tree: Any, dim: int = 0, pad_index: int = 0, pad_first: bool = False) -> Any:
    """Pad each process's tensors to the max size along ``dim`` across
    processes (reference `pad_across_processes`, `operations.py:628`)."""
    state = ProcessState()

    def _pad_leaf(x: Any) -> np.ndarray:
        arr = np.asarray(x)
        if arr.ndim == 0 or dim >= arr.ndim:
            return arr
        if state.num_processes == 1:
            return arr
        sizes = gather_object([arr.shape[dim]])
        max_size = max(sizes)
        if arr.shape[dim] == max_size:
            return arr
        pad_width = [(0, 0)] * arr.ndim
        if pad_first:
            pad_width[dim] = (max_size - arr.shape[dim], 0)
        else:
            pad_width[dim] = (0, max_size - arr.shape[dim])
        return np.pad(arr, pad_width, constant_values=pad_index)

    return jax.tree.map(_pad_leaf, tree)


def pad_input_tensors(tree: Any, batch_size: int, num_processes: int, dim: int = 0) -> Any:
    """Pad a batch so it divides evenly across processes by repeating the last
    row (reference `pad_input_tensors`, `operations.py:683`)."""
    remainder = batch_size % num_processes
    if remainder == 0:
        return tree
    pad_count = num_processes - remainder

    def _pad_leaf(x: Any) -> np.ndarray:
        arr = np.asarray(x)
        if arr.ndim == 0 or arr.shape[dim] != batch_size:
            return arr
        last = np.take(arr, [-1], axis=dim)
        reps = np.repeat(last, pad_count, axis=dim)
        return np.concatenate([arr, reps], axis=dim)

    return jax.tree.map(_pad_leaf, tree)


# ------------------------------------------------------------ object channel
def _object_to_bytes_array(obj: Any) -> np.ndarray:
    return np.frombuffer(pickle.dumps(obj), dtype=np.uint8)


def gather_object(objects: list[Any]) -> list[Any]:
    """All-gather arbitrary picklable objects; returns the flat list over all
    processes in rank order (reference `gather_object`, `operations.py:445`).

    The host-object control channel — the analog of
    `torch.distributed.all_gather_object` — built on padded uint8 tensor
    all-gather over the JAX runtime (SURVEY.md §5: host-level object channel).
    """
    # Payloads are legitimately per-process here; only the count is logged
    # (mirrors the ATX5xx alignment signature).
    _maybe_collective_log("gather_object", "gather_object")
    state = ProcessState()
    if state.num_processes == 1:
        return list(objects)
    payload = _object_to_bytes_array(objects)
    length = np.asarray([payload.size], dtype=np.int64)
    lengths = _process_allgather(length, tiled=False).reshape(-1)
    max_len = int(lengths.max())
    padded = np.zeros(max_len, dtype=np.uint8)
    padded[: payload.size] = payload
    all_payloads = _process_allgather(padded, tiled=False)
    result: list[Any] = []
    for rank in range(state.num_processes):
        blob = bytes(all_payloads[rank][: int(lengths[rank])])
        result.extend(pickle.loads(blob))
    return result


def broadcast_object_list(objects: list[Any], from_process: int = 0) -> list[Any]:
    """Broadcast picklable objects from one process (reference
    `broadcast_object_list`, `operations.py:560`).

    A real one-to-all: only the root's payload moves (two rounds — size,
    then bytes). The previous all-gather implementation shipped every
    process's (possibly None) payload to everyone, O(world) bandwidth on
    the dispatch_batches hot path.
    """
    _maybe_collective_log(
        "broadcast_object_list", f"broadcast_object_list[from={from_process}]"
    )
    state = ProcessState()
    if state.num_processes == 1:
        return list(objects)
    from jax.experimental import multihost_utils

    is_source = state.process_index == from_process
    payload = (
        _object_to_bytes_array(list(objects))
        if is_source
        else np.zeros(0, dtype=np.uint8)
    )
    length = multihost_utils.broadcast_one_to_all(
        np.asarray([payload.size], dtype=np.int64), is_source=is_source
    )
    buf = np.zeros(int(length[0]), dtype=np.uint8)
    if is_source:
        buf[: payload.size] = payload
    data = multihost_utils.broadcast_one_to_all(buf, is_source=is_source)
    return pickle.loads(bytes(np.asarray(data, dtype=np.uint8)))


def copy_tensor_to_devices(tree: Any, mesh: Mesh, spec: PartitionSpec | None = None) -> Any:
    """Form global sharded arrays from identical host data on every process
    (reference `copy_tensor_to_devices` for XLA, `operations.py:485`)."""
    spec = spec if spec is not None else PartitionSpec()
    sharding = NamedSharding(mesh, spec)
    return jax.tree.map(lambda x: jax.device_put(np.asarray(x), sharding), tree)


def apply_to_leaves(fn: Callable[[Any], Any], tree: Any) -> Any:
    """Compatibility shim for the reference's `recursively_apply`
    (`operations.py:84`) — pytrees make this trivial."""
    return jax.tree.map(fn, tree)

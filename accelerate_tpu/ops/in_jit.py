"""In-jit collective primitives & helpers.

The reference has no equivalent layer: its collectives (`utils/operations.py`)
always execute eagerly from Python via torch.distributed. On TPU the hot-path
collectives are XLA HLO ops compiled into the step function; this module gives
users and the framework a thin, named surface over them:

- `psum` / `pmean` / `pmax` / `pmin` — cross-replica reductions
- `all_gather_axis` — gather a sharded dim
- `ppermute` — neighbour exchange (ring collectives, pipeline transfers)
- `shard_map_over` — wrap a per-shard function over the global mesh

These matter when writing manual-collective regions (ring attention,
`parallel/ring.py`); plain GSPMD code never calls them — the compiler inserts
collectives from shardings.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence

import jax
from jax import lax
from jax.sharding import Mesh, PartitionSpec

# jax moved shard_map out of experimental in 0.5.x; support both homes.
shard_map = getattr(jax, "shard_map", None)
if shard_map is None:
    from jax.experimental.shard_map import shard_map

psum = lax.psum
pmean = lax.pmean
pmax = lax.pmax
pmin = lax.pmin
ppermute = lax.ppermute
axis_index = lax.axis_index


def all_gather_axis(x: jax.Array, axis_name: str, *, axis: int = 0, tiled: bool = True) -> jax.Array:
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def shard_map_over(
    fn: Callable[..., Any],
    mesh: Mesh,
    in_specs: Any,
    out_specs: Any,
    check_vma: bool = False,
) -> Callable[..., Any]:
    """`shard_map` with the framework mesh; per-shard code sees local blocks
    and may call the collectives above with the mesh axis names."""
    try:
        return shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=check_vma
        )
    except TypeError:
        # jax < 0.6 spells the replication check `check_rep`.
        return shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=check_vma
        )


def sequence_parallel_specs(
    mesh: Mesh, batch_size: int, batch_axes, axis_name: str
):
    """Shared entry scaffolding for the sequence-parallel attention schemes
    (ring / ulysses): returns ``(qkv_spec, mask_spec)`` with the batch dim
    sharded over ``batch_axes`` only when it divides (otherwise replicated —
    e.g. eval with a small batch on a large mesh; sequence sharding still
    applies)."""
    from jax.sharding import PartitionSpec as P

    batch_group = 1
    for a in batch_axes:
        batch_group *= mesh.shape[a]
    use_batch = (
        tuple(batch_axes) if batch_group > 1 and batch_size % batch_group == 0 else None
    )
    return P(use_batch, axis_name, None, None), P(use_batch, axis_name)


def ring_neighbors(axis_name: str, n: int) -> list[tuple[int, int]]:
    """Permutation pairs sending shard i -> i+1 (mod n) along a mesh axis."""
    return [(i, (i + 1) % n) for i in range(n)]

"""Ulysses-style sequence parallelism: all-to-all head/sequence exchange.

The second of the two standard long-context schemes (the task the reference
delegates entirely to Megatron flags — SURVEY.md §2.2): `ring_attention.py`
keeps Q local and rotates KV around the ring; Ulysses (DeepSpeed-Ulysses)
instead re-shards *within* attention. Outside attention every tensor is
sequence-sharded; for the attention op itself an all-to-all converts the
layout

    (B, S/n, H, h)  --all_to_all-->  (B, S, H/n, h)

so each device runs EXACT full-sequence attention over its slice of heads
(any local kernel — here the Pallas flash path — with no chunk-granular
masking), and a second all-to-all converts back. Communication is
2x all-to-all of the qkv/o tensors per layer vs ring's (n-1) KV rotations:
cheaper when heads divide the mesh axis and S is very long; ring wins when
H is small or KV is much smaller than Q (GQA). Both ride the ICI.

Trade-offs vs ring:
- needs ``num_heads % n == 0`` AND ``num_kv_heads % n == 0`` (heads are the
  parallel resource during attention);
- exact attention locally -> no chunk-causality bookkeeping, the flash
  kernel's own causal masking applies;
- differentiable end-to-end through `jax.lax.all_to_all` + the flash
  custom VJP: no hand-written backward.
"""

from __future__ import annotations

import math
from typing import Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..parallel.mesh import BATCH_AXES, SEQUENCE_AXIS


def _ulysses_local(q, k, v, mask, *, axis_name, causal, scale, window=None):
    """Per-device body under shard_map. q/k/v: (B, S/n, H, h) local."""
    from .flash_attention import flash_attention

    # (B, S/n, H, h) -> (B, S, H/n, h): split heads (axis 2), gather seq (1).
    qh = jax.lax.all_to_all(q, axis_name, split_axis=2, concat_axis=1, tiled=True)
    kh = jax.lax.all_to_all(k, axis_name, split_axis=2, concat_axis=1, tiled=True)
    vh = jax.lax.all_to_all(v, axis_name, split_axis=2, concat_axis=1, tiled=True)
    if mask is not None:
        # (B, S/n) -> (B, S): every device needs the full key mask.
        mask = jax.lax.all_gather(mask, axis_name, axis=1, tiled=True)
    out = flash_attention(
        qh, kh, vh, causal=causal, segment_mask=mask, scale=scale, window=window
    )
    # (B, S, H/n, h) -> (B, S/n, H, h)
    return jax.lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2, tiled=True)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    kv_mask: jax.Array | None = None,
    scale: float | None = None,
    mesh: Mesh | None = None,
    axis_name: str = SEQUENCE_AXIS,
    batch_axes: Sequence[str] = BATCH_AXES,
    window: int | None = None,
) -> jax.Array:
    """Sequence-parallel exact attention over (B, S, H, h) global arrays.

    ``window`` = Mistral-style sliding window, applied by the fused kernel
    after the head exchange (each device then holds the full sequence for
    its head subset, so the band anchors are exact).

    Same call contract as `ring_attention` (S sharded over ``axis_name``,
    B over ``batch_axes``; callable inside or outside jit; degrades to
    plain local attention when the sequence axis is 1). ``kv_mask`` is a
    (B, S) key-padding mask, sequence-sharded like k/v — but NOTE: the
    masked path runs the unfused O(S^2) oracle over the gathered sequence
    (the flash kernel has no per-key masking), so it is only suitable for
    short/medium S; padded long-context batches should use ring attention,
    whose chunked einsum path handles masks at O(S^2/n) memory.
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if mesh is None:
        from ..state import AcceleratorState

        mesh = AcceleratorState().mesh
    n = mesh.shape[axis_name]
    B, S, H, h = q.shape
    K = k.shape[2]
    if n > 1:
        if H % n != 0 or K % n != 0:
            raise ValueError(
                f"ulysses_attention needs num_heads ({H}) and num_kv_heads "
                f"({K}) divisible by the '{axis_name}' axis size ({n}); "
                "use ring attention for head counts that don't divide."
            )
        if S % n != 0:
            raise ValueError(f"sequence length {S} not divisible by {axis_name}={n}")

    import functools

    from .in_jit import sequence_parallel_specs, shard_map_over

    spec, mask_spec = sequence_parallel_specs(mesh, B, batch_axes, axis_name)

    body = functools.partial(
        _ulysses_local, axis_name=axis_name, causal=causal, scale=scale,
        window=window,
    )
    if kv_mask is not None:
        kv_mask = kv_mask.astype(bool)
    in_specs = (spec, spec, spec, mask_spec if kv_mask is not None else None)
    fn = shard_map_over(body, mesh, in_specs, spec)
    return fn(q, k, v, kv_mask)

"""Fused flash attention (Pallas, TPU).

The reference has no attention kernels at all — fused attention arrives via
torch SDPA / Megatron CUDA kernels (SURVEY.md §2.2: "fused softmax" listed as
a native dependency to replace). Here it is a first-class TPU kernel:

- forward: online-softmax with BOTH Q and KV blocked through the grid —
  VMEM use is O(block²), independent of sequence length, so the kernel
  compiles at the long-context lengths flash attention exists for. The
  softmax running state (m, l, acc) lives in VMEM scratch carried across
  the innermost (KV) grid axis;
- backward: custom VJP with two Pallas kernels (dq accumulated over KV
  blocks, dk/dv accumulated over Q blocks), same blocked-grid structure,
  using the saved logsumexp + delta trick;
- GQA: query heads map onto kv heads via index maps (no kv replication in
  HBM); backward folds group gradients outside the kernel;
- causal masking by block skipping (upper-triangle blocks are visited but
  skipped with `pl.when` — no FLOPs, no VMEM traffic beyond the prefetch).

Layouts follow the framework convention (B, S, H, h); kernels run in
(B, H, S, h). Falls back to the XLA reference implementation
(`models/layers.py:dot_product_attention`) for shapes the kernel does not
support (tiny S, explicit padding masks) so callers can use one entry point.
Runs in interpreter mode automatically on CPU (tests/CI).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30
# 512 empirically: ~3-7x faster than 128 on v5e at S=2048 (loop/semaphore
# overhead amortizes; the (512, 512) f32 s-matrix stays well under VMEM).
DEFAULT_BLOCK = 512
# Staged-K+V byte budget for the resident-KV kernels: below this the whole
# KV sequence stays in VMEM per (B, H) program (fastest — no KV re-fetch per
# Q block, measured ~8% whole-model MFU at S=2048); above it the blocked
# kernels keep VMEM O(block^2) so arbitrarily long sequences compile.
_RESIDENT_KV_BUDGET = 4 * 1024 * 1024


def _use_resident(S: int, h: int, dtype) -> bool:
    # The blocked-KV path with its adaptive 1024 block measured 1.5-1.6x
    # FASTER than the resident kernels from S=4096 up on v5e (equal-token
    # sweeps: 31 vs 50 ms at 4k, 41 vs 61 ms at 8k, fwd+bwd); resident
    # still wins at S=2048 (27 vs 33 ms). Keep resident below the
    # crossover, and only while the staged KV fits its VMEM budget.
    return S < 4096 and 2 * S * h * jnp.dtype(dtype).itemsize <= _RESIDENT_KV_BUDGET


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


def _compiler_params():
    """Mark (B, H, Q-blocks) parallel, KV-blocks sequential (the scratch
    carry). Best-effort across pallas versions."""
    try:
        return pltpu.TPUCompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary")
        )
    except Exception:  # pragma: no cover - version dependent
        return None


def _call_kwargs(interpret):
    kwargs = {"interpret": interpret}
    params = _compiler_params()
    if params is not None and not interpret:
        kwargs["compiler_params"] = params
    return kwargs




def _block_live(q_start, block_q, k_start, *, causal, valid, window=None, block_k=None):
    """Should this (Q-block, KV-block) tile be computed at all?"""
    live = (q_start + block_q - 1 >= k_start) if causal else (k_start < valid)
    if window is not None:
        # Sliding window: key c visible from row r iff r - c < window. The
        # tile is dead when even its newest key is out of every row's band.
        bk = block_k if block_k is not None else block_q
        live = jnp.logical_and(live, q_start - (k_start + bk - 1) < window)
    return live


def _mask_scores(s, q_start, k_start, *, causal, valid, window=None):
    """Apply causal / window / padded-column masking to a (bq, bk) tile."""
    rows = q_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
    cols = k_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    if causal:
        keep = rows >= cols
        if window is not None:
            keep = jnp.logical_and(keep, rows - cols < window)
        return jnp.where(keep, s, _NEG_INF)
    keep = cols < valid
    if window is not None:
        keep = jnp.logical_and(keep, rows - cols < window)
    return jnp.where(keep, s, _NEG_INF)


# ---------------------------------------------------- resident-KV kernels
# Original single-pass kernels: K/V for the whole sequence stay staged in
# VMEM while one Q block loops over them — fastest when they fit (short/
# medium S), used below _RESIDENT_KV_BUDGET bytes of staged KV.
def _fwd_kernel_resident(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, block, causal, seq_len, valid, window=None):
    qi = pl.program_id(2)
    # Keep matmul operands in their native (bf16) dtype: the MXU runs bf16 x
    # bf16 -> f32 at full rate, while f32 x f32 passes take a multiple of the
    # time. Accumulation stays f32 via preferred_element_type.
    q = q_ref[0, 0]  # (bq, h)
    bq = q.shape[0]
    head_dim = q.shape[1]
    q_start = qi * bq
    n_blocks = seq_len // block
    # Causal: KV blocks strictly above the diagonal contribute nothing.
    hi = jnp.minimum((q_start + bq + block - 1) // block, n_blocks) if causal else n_blocks
    # Sliding window: KV blocks entirely below the band contribute nothing
    # either — the loop starts at the window's oldest live block.
    lo = jnp.maximum((q_start - (window - 1)) // block, 0) if window is not None else 0

    def body(j, carry):
        m, l, acc = carry
        k = k_ref[0, 0, pl.ds(j * block, block), :]  # (bk, h)
        v = v_ref[0, 0, pl.ds(j * block, block), :]
        s = scale * jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # (bq, bk) f32
        if causal:
            rows = q_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            cols = j * block + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            keep = rows >= cols
            if window is not None:
                keep = jnp.logical_and(keep, rows - cols < window)
            s = jnp.where(keep, s, _NEG_INF)
        elif valid < seq_len or window is not None:
            cols = j * block + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            keep = cols < valid
            if window is not None:
                rows = q_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
                keep = jnp.logical_and(keep, rows - cols < window)
            s = jnp.where(keep, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        # p is cast to the kv dtype for the MXU (standard flash practice;
        # p in [0,1] so bf16's relative precision is adequate).
        acc = acc * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return m_new, l, acc

    m0 = jnp.full((bq, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    acc0 = jnp.zeros((bq, head_dim), jnp.float32)
    m, l, acc = jax.lax.fori_loop(lo, hi, body, (m0, l0, acc0))
    l_safe = jnp.maximum(l, 1e-30)
    o_ref[0, 0] = (acc / l_safe).astype(o_ref.dtype)
    lse_ref[0, 0] = (m + jnp.log(l_safe)).astype(jnp.float32)  # (bq, 1)



def _fwd_resident(q, k, v, *, scale, block, causal, interpret, valid, window=None):
    B, H, S, h = q.shape
    K = k.shape[1]
    group = H // K
    grid = (B, H, S // block)
    kernel = functools.partial(
        _fwd_kernel_resident, scale=scale, block=block, causal=causal,
        seq_len=S, valid=valid, window=window,
    )
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block, h), lambda b, hh, qi: (b, hh, qi, 0)),
            pl.BlockSpec((1, 1, S, h), lambda b, hh, qi: (b, hh // group, 0, 0)),
            pl.BlockSpec((1, 1, S, h), lambda b, hh, qi: (b, hh // group, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block, h), lambda b, hh, qi: (b, hh, qi, 0)),
            pl.BlockSpec((1, 1, block, 1), lambda b, hh, qi: (b, hh, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, S, h), q.dtype),
            jax.ShapeDtypeStruct((B, H, S, 1), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return o, lse



def _dq_kernel_resident(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *, scale, block, causal, seq_len, valid, window=None):
    qi = pl.program_id(2)
    q = q_ref[0, 0]
    do = do_ref[0, 0]
    lse = lse_ref[0, 0]  # (bq, 1)
    delta = delta_ref[0, 0]
    bq, head_dim = q.shape
    q_start = qi * bq
    n_blocks = seq_len // block
    hi = jnp.minimum((q_start + bq + block - 1) // block, n_blocks) if causal else n_blocks
    lo = jnp.maximum((q_start - (window - 1)) // block, 0) if window is not None else 0

    def body(j, dq):
        k = k_ref[0, 0, pl.ds(j * block, block), :]
        v = v_ref[0, 0, pl.ds(j * block, block), :]
        s = scale * jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        if causal or valid < seq_len or window is not None:
            rows = q_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            cols = j * block + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            keep = (rows >= cols) if causal else (cols < valid)
            if window is not None:
                keep = jnp.logical_and(keep, rows - cols < window)
            s = jnp.where(keep, s, _NEG_INF)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = (p * (dp - delta)).astype(k.dtype)
        return dq + scale * jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    dq = jax.lax.fori_loop(lo, hi, body, jnp.zeros((bq, head_dim), jnp.float32))
    dq_ref[0, 0] = dq.astype(dq_ref.dtype)



def _dkv_kernel_resident(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref, *, scale, block, causal, seq_len, valid, window=None):
    j = pl.program_id(2)
    k = k_ref[0, 0]  # (bk, h)
    v = v_ref[0, 0]
    bk, head_dim = k.shape
    k_start = j * bk
    n_blocks = seq_len // block
    lo = (k_start // block) if causal else 0
    # Window: q rows past k_start+bk-1+window-1 see none of this k block.
    hi = (
        jnp.minimum((k_start + bk - 1 + window) // block + 1, n_blocks)
        if window is not None
        else n_blocks
    )

    def body(i, carry):
        dk, dv = carry
        q = q_ref[0, 0, pl.ds(i * block, block), :]
        do = do_ref[0, 0, pl.ds(i * block, block), :]
        lse = lse_ref[0, 0, pl.ds(i * block, block), :]  # (bq, 1)
        delta = delta_ref[0, 0, pl.ds(i * block, block), :]
        s = scale * jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        if causal or valid < seq_len or window is not None:
            rows = i * block + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            cols = k_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            keep = (rows >= cols) if causal else (cols < valid)
            if window is not None:
                keep = jnp.logical_and(keep, rows - cols < window)
            s = jnp.where(keep, s, _NEG_INF)
        p = jnp.exp(s - lse)  # (bq, bk) f32
        dv = dv + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = (p * (dp - delta)).astype(q.dtype)
        dk = dk + scale * jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return dk, dv

    init = (
        jnp.zeros((bk, head_dim), jnp.float32),
        jnp.zeros((bk, head_dim), jnp.float32),
    )
    dk, dv = jax.lax.fori_loop(lo, hi, body, init)
    dk_ref[0, 0] = dk.astype(dk_ref.dtype)
    dv_ref[0, 0] = dv.astype(dv_ref.dtype)



def _bwd_resident(scale, block, causal, interpret, valid, residuals, g, window=None):
    q, k, v, o, lse = residuals
    B, H, S, h = q.shape
    K = k.shape[1]
    group = H // K
    do = g
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1, keepdims=True)  # (B,H,S,1)

    grid = (B, H, S // block)
    dq = pl.pallas_call(
        functools.partial(_dq_kernel_resident, scale=scale, block=block, causal=causal, seq_len=S, valid=valid, window=window),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block, h), lambda b, hh, qi: (b, hh, qi, 0)),
            pl.BlockSpec((1, 1, S, h), lambda b, hh, qi: (b, hh // group, 0, 0)),
            pl.BlockSpec((1, 1, S, h), lambda b, hh, qi: (b, hh // group, 0, 0)),
            pl.BlockSpec((1, 1, block, h), lambda b, hh, qi: (b, hh, qi, 0)),
            pl.BlockSpec((1, 1, block, 1), lambda b, hh, qi: (b, hh, qi, 0)),
            pl.BlockSpec((1, 1, block, 1), lambda b, hh, qi: (b, hh, qi, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block, h), lambda b, hh, qi: (b, hh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((B, H, S, h), q.dtype),
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    grid_kv = (B, H, S // block)
    dk_h, dv_h = pl.pallas_call(
        functools.partial(_dkv_kernel_resident, scale=scale, block=block, causal=causal, seq_len=S, valid=valid, window=window),
        grid=grid_kv,
        in_specs=[
            pl.BlockSpec((1, 1, S, h), lambda b, hh, j: (b, hh, 0, 0)),
            pl.BlockSpec((1, 1, block, h), lambda b, hh, j: (b, hh // group, j, 0)),
            pl.BlockSpec((1, 1, block, h), lambda b, hh, j: (b, hh // group, j, 0)),
            pl.BlockSpec((1, 1, S, h), lambda b, hh, j: (b, hh, 0, 0)),
            pl.BlockSpec((1, 1, S, 1), lambda b, hh, j: (b, hh, 0, 0)),
            pl.BlockSpec((1, 1, S, 1), lambda b, hh, j: (b, hh, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block, h), lambda b, hh, j: (b, hh, j, 0)),
            pl.BlockSpec((1, 1, block, h), lambda b, hh, j: (b, hh, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, S, h), q.dtype),
            jax.ShapeDtypeStruct((B, H, S, h), q.dtype),
        ],
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    if group > 1:
        # Fold query-head-group gradients onto the shared kv heads.
        dk = dk_h.reshape(B, K, group, S, h).sum(axis=2).astype(k.dtype)
        dv = dv_h.reshape(B, K, group, S, h).sum(axis=2).astype(v.dtype)
    else:
        dk, dv = dk_h.astype(k.dtype), dv_h.astype(v.dtype)
    return dq, dk, dv




# ------------------------------------------------------------------- forward
def _banded_grid(nq: int, block: int, causal: bool, window, group: int, clamp_hi: int | None = None):
    """Shared banded-KV/Q-grid setup for the windowed kernels: (n_eff,
    window_grid, index_map). `clamp_hi` picks the clamp edge — None for the
    fwd/dq KV axis (clamped at 0, offset qi - (n_eff-1) + i), or nq-1 for
    the dkv Q axis (offset ki + i). All three kernels reconstruct
    k_start/q_start from the SAME n_eff, so this must stay the single
    source of the band width."""
    if window is not None and causal:
        n_eff = min(nq, (window + block - 1) // block + 1)
        window_grid = n_eff < nq
    else:
        n_eff, window_grid = nq, False

    if clamp_hi is None:
        def index_map(b, hh, qi, ki):
            if window_grid:
                return (b, hh // group, jnp.maximum(qi - (n_eff - 1) + ki, 0), 0)
            return (b, hh // group, ki, 0)
    else:
        def index_map(b, hh, ki, qi):
            if window_grid:
                return (b, hh // group, jnp.minimum(ki + qi, clamp_hi), 0)
            return (b, hh // group, qi, 0)

    return n_eff, window_grid, index_map


def _fwd_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref, m_ref, l_ref, acc_ref,
    *, scale, block_q, block_k, causal, valid, window=None, window_grid=False,
):
    qi, ki = pl.program_id(2), pl.program_id(3)
    nk = pl.num_programs(3)
    q_start = qi * block_q
    if window_grid:
        # Banded grid: the KV-block axis only spans the window's live
        # diagonal band — ki indexes positions [qi - (nk-1), qi]. k_start
        # may be negative at the left edge; those tiles mask to nothing
        # (their fetch is clamped to block 0 by the index map).
        k_start = (qi - (nk - 1) + ki) * block_k
    else:
        k_start = ki * block_k

    @pl.when(ki == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # Causal: blocks entirely above the diagonal contribute nothing;
    # a sliding window additionally kills blocks below the band.
    run = _block_live(
        q_start, block_q, k_start,
        causal=causal, valid=valid, window=window, block_k=block_k,
    )
    if window_grid:
        # Left-edge band positions before the sequence start do not exist;
        # without this the clamped fetch would re-read block 0 under a
        # shifted (wrong) mask and double-count its keys.
        run = jnp.logical_and(run, k_start >= 0)

    @pl.when(run)
    def _block():
        # Keep matmul operands in their native (bf16) dtype: the MXU runs
        # bf16 x bf16 -> f32 at full rate; accumulation stays f32 via
        # preferred_element_type.
        q = q_ref[0, 0]  # (bq, h)
        k = k_ref[0, 0]  # (bk, h)
        v = v_ref[0, 0]
        s = scale * jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # (bq, bk) f32
        s = _mask_scores(
            s, q_start, k_start, causal=causal, valid=valid, window=window
        )
        m_prev, l_prev = m_ref[...], l_ref[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[...] = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        # p cast to the kv dtype for the MXU (standard flash practice; p in
        # [0,1] so bf16 relative precision is adequate).
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[...] = m_new

    @pl.when(ki == nk - 1)
    def _finish():
        l_safe = jnp.maximum(l_ref[...], 1e-30)
        o_ref[0, 0] = (acc_ref[...] / l_safe).astype(o_ref.dtype)
        lse_ref[0, 0] = (m_ref[...] + jnp.log(l_safe)).astype(jnp.float32)


def _fwd(q, k, v, *, scale, block, causal, interpret, valid, window=None):
    B, H, S, h = q.shape
    if _use_resident(S, h, k.dtype):
        return _fwd_resident(
            q, k, v, scale=scale, block=block, causal=causal,
            interpret=interpret, valid=valid, window=window,
        )
    K = k.shape[1]
    group = H // K
    nq = S // block
    # With a sliding window, the KV-grid axis spans only the live band —
    # dead tiles are never fetched or visited, so work (and DMA) scales
    # with O(S * window) instead of O(S^2).
    n_eff, window_grid, kv_index = _banded_grid(nq, block, causal, window, group)
    grid = (B, H, nq, n_eff)
    kernel = functools.partial(
        _fwd_kernel, scale=scale, block_q=block, block_k=block, causal=causal,
        valid=valid, window=window, window_grid=window_grid,
    )
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block, h), lambda b, hh, qi, ki: (b, hh, qi, 0)),
            pl.BlockSpec((1, 1, block, h), kv_index),
            pl.BlockSpec((1, 1, block, h), kv_index),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block, h), lambda b, hh, qi, ki: (b, hh, qi, 0)),
            pl.BlockSpec((1, 1, block, 1), lambda b, hh, qi, ki: (b, hh, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, S, h), q.dtype),
            jax.ShapeDtypeStruct((B, H, S, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block, 1), jnp.float32),   # m
            pltpu.VMEM((block, 1), jnp.float32),   # l
            pltpu.VMEM((block, h), jnp.float32),   # acc
        ],
        **_call_kwargs(interpret),
    )(q, k, v)
    return o, lse


# ------------------------------------------------------------------ backward
def _dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_acc_ref,
    *, scale, block_q, block_k, causal, valid, window=None, window_grid=False,
):
    qi, ki = pl.program_id(2), pl.program_id(3)
    nk = pl.num_programs(3)
    q_start = qi * block_q
    if window_grid:
        k_start = (qi - (nk - 1) + ki) * block_k
    else:
        k_start = ki * block_k

    @pl.when(ki == 0)
    def _init():
        dq_acc_ref[...] = jnp.zeros_like(dq_acc_ref)

    run = _block_live(
        q_start, block_q, k_start,
        causal=causal, valid=valid, window=window, block_k=block_k,
    )
    if window_grid:
        run = jnp.logical_and(run, k_start >= 0)

    @pl.when(run)
    def _block():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0]
        delta = delta_ref[0, 0]
        s = scale * jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        s = _mask_scores(
            s, q_start, k_start, causal=causal, valid=valid, window=window
        )
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = (p * (dp - delta)).astype(k.dtype)
        dq_acc_ref[...] += scale * jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(ki == nk - 1)
    def _finish():
        dq_ref[0, 0] = dq_acc_ref[...].astype(dq_ref.dtype)


def _dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    dk_acc_ref, dv_acc_ref,
    *, scale, block_q, block_k, causal, valid, window=None, window_grid=False,
    n_q_blocks=None,
):
    # Grid: (B, H, KV-blocks, Q-blocks) — Q is the innermost carried axis.
    ki, qi = pl.program_id(2), pl.program_id(3)
    nq = pl.num_programs(3)
    k_start = ki * block_k
    if window_grid:
        # Banded: causal+window means only q blocks [ki, ki + nq) touch
        # this k block; right-edge tiles past the sequence are dead (their
        # fetch is clamped to the last block by the index map).
        q_start = (ki + qi) * block_q
    else:
        q_start = qi * block_q

    @pl.when(qi == 0)
    def _init():
        dk_acc_ref[...] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[...] = jnp.zeros_like(dv_acc_ref)

    run = _block_live(
        q_start, block_q, k_start,
        causal=causal, valid=valid, window=window, block_k=block_k,
    )
    if window_grid:
        run = jnp.logical_and(run, ki + qi < n_q_blocks)

    @pl.when(run)
    def _block():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0]
        delta = delta_ref[0, 0]
        s = scale * jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        s = _mask_scores(
            s, q_start, k_start, causal=causal, valid=valid, window=window
        )
        p = jnp.exp(s - lse)  # (bq, bk) f32
        dv_acc_ref[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = (p * (dp - delta)).astype(q.dtype)
        dk_acc_ref[...] += scale * jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(qi == nq - 1)
    def _finish():
        dk_ref[0, 0] = dk_acc_ref[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc_ref[...].astype(dv_ref.dtype)


def dq_call(q, k, v, do, lse, delta, *, scale, block, causal, interpret, valid, window=None):
    """dq for one (q, kv) pair via the blocked kernel. Shapes (B, H, S, h);
    exposed for ring attention's per-chunk backward."""
    B, H, S, h = q.shape
    group = H // k.shape[1]
    nq = S // block
    n_eff, window_grid, kv_index = _banded_grid(nq, block, causal, window, group)
    grid = (B, H, nq, n_eff)
    return pl.pallas_call(
        functools.partial(
            _dq_kernel, scale=scale, block_q=block, block_k=block, causal=causal,
            valid=valid, window=window, window_grid=window_grid,
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block, h), lambda b, hh, qi, ki: (b, hh, qi, 0)),
            pl.BlockSpec((1, 1, block, h), kv_index),
            pl.BlockSpec((1, 1, block, h), kv_index),
            pl.BlockSpec((1, 1, block, h), lambda b, hh, qi, ki: (b, hh, qi, 0)),
            pl.BlockSpec((1, 1, block, 1), lambda b, hh, qi, ki: (b, hh, qi, 0)),
            pl.BlockSpec((1, 1, block, 1), lambda b, hh, qi, ki: (b, hh, qi, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block, h), lambda b, hh, qi, ki: (b, hh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((B, H, S, h), q.dtype),
        scratch_shapes=[pltpu.VMEM((block, h), jnp.float32)],
        **_call_kwargs(interpret),
    )(q, k, v, do, lse, delta)


def dkv_call(q, k, v, do, lse, delta, *, scale, block, causal, interpret, valid, window=None):
    """(dk, dv) for one (q, kv) pair via the blocked kernel — per expanded
    query head (no GQA fold; the caller folds groups). Shapes (B, H, S, h)."""
    B, H, S, h = q.shape
    group = H // k.shape[1]
    nq = S // block
    n_eff, window_grid, _q_index = _banded_grid(
        nq, block, causal, window, group=1, clamp_hi=nq - 1
    )
    q_index = _q_index
    grid_kv = (B, H, nq, n_eff)
    return pl.pallas_call(
        functools.partial(
            _dkv_kernel, scale=scale, block_q=block, block_k=block, causal=causal,
            valid=valid, window=window, window_grid=window_grid, n_q_blocks=nq,
        ),
        grid=grid_kv,
        in_specs=[
            pl.BlockSpec((1, 1, block, h), q_index),
            pl.BlockSpec((1, 1, block, h), lambda b, hh, ki, qi: (b, hh // group, ki, 0)),
            pl.BlockSpec((1, 1, block, h), lambda b, hh, ki, qi: (b, hh // group, ki, 0)),
            pl.BlockSpec((1, 1, block, h), q_index),
            pl.BlockSpec((1, 1, block, 1), q_index),
            pl.BlockSpec((1, 1, block, 1), q_index),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block, h), lambda b, hh, ki, qi: (b, hh, ki, 0)),
            pl.BlockSpec((1, 1, block, h), lambda b, hh, ki, qi: (b, hh, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, S, h), q.dtype),
            jax.ShapeDtypeStruct((B, H, S, h), q.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block, h), jnp.float32),
            pltpu.VMEM((block, h), jnp.float32),
        ],
        **_call_kwargs(interpret),
    )(q, k, v, do, lse, delta)


def fold_gqa_groups(dk_h, dv_h, K, k_dtype, v_dtype):
    """Sum per-query-head kv grads onto the shared kv heads."""
    B, H, S, h = dk_h.shape
    group = H // K
    if group > 1:
        dk = dk_h.reshape(B, K, group, S, h).sum(axis=2).astype(k_dtype)
        dv = dv_h.reshape(B, K, group, S, h).sum(axis=2).astype(v_dtype)
        return dk, dv
    return dk_h.astype(k_dtype), dv_h.astype(v_dtype)


# ------------------------------------------------ SPMD partitioning (GSPMD)
# pallas_call lowers to an opaque custom-call; without a partitioning rule
# GSPMD replicates the kernel with UNSHARDED operands on every chip — at
# pod scale that is a full-global-batch 30+ GiB allocation per device
# (caught by tests/test_pod_aot.py on a deviceless v5e-256 compile). The
# kernels are embarrassingly parallel over batch and heads, so declare
# exactly that via `custom_partitioning`: batch/head partitioning passes
# through (the head factor must divide BOTH H and the GQA K), sequence and
# head_dim replicate within each shard.
from jax.experimental.custom_partitioning import custom_partitioning
from jax.sharding import NamedSharding, PartitionSpec


def _axis_group(mesh, entry) -> int:
    axes = (entry,) if isinstance(entry, str) else tuple(entry)
    return int(math.prod(mesh.shape[a] for a in axes))


def _bh_sharding(mesh, sharding, H: int, K: int, ndim: int = 4) -> NamedSharding:
    """Sanitize to batch/head-only partitioning ((B, H|K, S, h) layout)."""
    spec = list(sharding.spec) + [None] * (ndim - len(tuple(sharding.spec)))
    b_ax, h_ax = spec[0], spec[1]
    if h_ax is not None and (H % _axis_group(mesh, h_ax) or K % _axis_group(mesh, h_ax)):
        h_ax = None
    return NamedSharding(mesh, PartitionSpec(b_ax, h_ax, *([None] * (ndim - 2))))


def _make_bh_partitioned(inner, n_out: int, sharding_rule: str):
    """Wrap `inner(*tensors, *statics)` (all tensors (B, H|K, S, *)) so the
    partitioner shards it over batch/heads and runs the kernel per shard.
    ``sharding_rule`` is the Shardy propagation rule (einsum-like); the
    partition callback owns the per-shard lowering and re-sanitizes the
    shardings (head factor must divide both H and the GQA K) either way."""

    def _hk(arg_shapes):
        return arg_shapes[0].shape[1], arg_shapes[1].shape[1]

    def infer(*cb_args):
        *_statics, mesh, arg_shapes, result_shape = cb_args
        H, K = _hk(arg_shapes)
        sh = _bh_sharding(mesh, arg_shapes[0].sharding, H, K)
        if n_out == 1:
            return sh
        outs = jax.tree.leaves(result_shape)
        return tuple(
            NamedSharding(mesh, sh.spec) for _ in range(len(outs))
        )

    def partition(*cb_args):
        *statics, mesh, arg_shapes, result_shape = cb_args
        H, K = _hk(arg_shapes)
        base = _bh_sharding(mesh, arg_shapes[0].sharding, H, K)
        arg_sh = tuple(
            _bh_sharding(mesh, base, H, K, ndim=len(a.shape)) for a in arg_shapes
        )
        outs = jax.tree.leaves(result_shape)
        out_sh = tuple(
            _bh_sharding(mesh, base, H, K, ndim=len(o.shape)) for o in outs
        )
        if n_out == 1:
            out_sh = out_sh[0]

        def lower(*tensors):
            return inner(*tensors, *statics)

        return mesh, lower, out_sh, arg_sh

    wrapped = custom_partitioning(inner, static_argnums=tuple(range(
        _N_TENSORS[inner], _N_TENSORS[inner] + 6
    )))
    try:
        wrapped.def_partition(
            partition=partition,
            infer_sharding_from_operands=infer,
            sharding_rule=sharding_rule,
        )
    except TypeError:
        # jax < 0.5.x: def_partition has no sharding_rule (the einsum-like
        # rule string newer shard_map tracing wants); the callbacks alone
        # carry the same partitioning.
        wrapped.def_partition(
            partition=partition,
            infer_sharding_from_operands=infer,
        )
    return wrapped


def _fwd_tensors(q, k, v, scale, block, causal, interpret, valid, window):
    return _fwd(q, k, v, scale=scale, block=block, causal=causal,
                interpret=interpret, valid=valid, window=window)


def _bwd_tensors(q, k, v, o, lse, g, scale, block, causal, interpret, valid, window):
    do = g
    if _use_resident(q.shape[2], q.shape[3], k.dtype):
        return _bwd_resident(
            scale, block, causal, interpret, valid, (q, k, v, o, lse), g,
            window=window,
        )
    delta = jnp.sum(
        do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1, keepdims=True
    )
    kwargs = dict(scale=scale, block=block, causal=causal, interpret=interpret,
                  valid=valid, window=window)
    dq = dq_call(q, k, v, do, lse, delta, **kwargs)
    dk_h, dv_h = dkv_call(q, k, v, do, lse, delta, **kwargs)
    dk, dv = fold_gqa_groups(dk_h, dv_h, k.shape[1], k.dtype, v.dtype)
    return dq, dk, dv


_N_TENSORS = {_fwd_tensors: 3, _bwd_tensors: 6}
# i=batch, j=q-heads, g=kv-heads, s=seq, d=head_dim, e=lse trailing unit.
_fwd_p = _make_bh_partitioned(
    _fwd_tensors, n_out=2,
    sharding_rule="i j s d, i g s d, i g s d -> i j s d, i j s e",
)
_bwd_p = _make_bh_partitioned(
    _bwd_tensors, n_out=3,
    sharding_rule=(
        "i j s d, i g s d, i g s d, i j s d, i j s e, i j s d "
        "-> i j s d, i g s d, i g s d"
    ),
)


def _call_partitioned(p_fn, inner, args):
    try:
        return p_fn(*args)
    except TypeError:
        # jax < 0.5: custom_partitioning passes its static_args as a LIST
        # bind param, which is unhashable under shard_map tracing. A
        # per-shard call is already partitioned by the enclosing shard_map,
        # so the raw kernel is equivalent there.
        return inner(*args)


# --------------------------------------------------------------- entry point
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash(q, k, v, scale, block, causal, interpret, valid, window):
    o, _ = _call_partitioned(
        _fwd_p, _fwd_tensors, (q, k, v, scale, block, causal, interpret, valid, window)
    )
    return o


def _flash_fwd(q, k, v, scale, block, causal, interpret, valid, window):
    o, lse = _call_partitioned(
        _fwd_p, _fwd_tensors, (q, k, v, scale, block, causal, interpret, valid, window)
    )
    return o, (q, k, v, o, lse)


def _flash_bwd(scale, block, causal, interpret, valid, window, residuals, g):
    q, k, v, o, lse = residuals
    return _call_partitioned(
        _bwd_p, _bwd_tensors,
        (q, k, v, o, lse, g, scale, block, causal, interpret, valid, window),
    )


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    segment_mask: jax.Array | None = None,
    block_size: int | None = None,
    scale: float | None = None,
    interpret: bool | None = None,
    window: int | None = None,
) -> jax.Array:
    """Fused attention over (B, S, H, h) queries and (B, T, K, h) kv (GQA).

    ``window`` enables Mistral-style sliding-window attention IN the
    kernels (forward and backward): key c is visible from row r iff
    ``r - c < window``; band-dead tiles are neither fetched nor computed —
    the KV/Q grid axes span only the live diagonal band, so window-bounded
    contexts run at O(S * window) instead of O(S^2).

    Falls back to the XLA reference path when the shape is out of kernel
    territory (S not a multiple of the block, or an explicit padding mask —
    packed/padded batches route through the oracle until the kernel grows
    segment-id support)."""
    B, S, H, h = q.shape
    T, K = k.shape[1], k.shape[2]
    if H % K != 0:
        raise ValueError(f"num_heads {H} not divisible by num_kv_heads {K}")
    scale = scale if scale is not None else 1.0 / math.sqrt(h)
    if segment_mask is not None or S != T or S < 16:
        from ..models.layers import dot_product_attention

        if window is not None:
            # Queries are the last S of T absolute positions (the KV-cache
            # decode convention); anchoring at row index 0 would make the
            # band a no-op for single-token decode.
            rows = (T - S) + jnp.arange(S)[:, None]
            cols = jnp.arange(T)[None, :]
            band = jnp.broadcast_to((rows - cols < window), (B, S, T))
            segment_mask = (
                band
                if segment_mask is None
                else band
                & (segment_mask[:, None, :] if segment_mask.ndim == 2 else segment_mask).astype(bool)
            )
        return dot_product_attention(q, k, v, mask=segment_mask, causal=causal, scale=scale)
    interpret = _interpret_default() if interpret is None else interpret
    if block_size is None:
        # The persisted autotune table (ops/autotune.py) wins when it has
        # an entry for this (chip, seq, head_dim, dtype) — or when the
        # ATX_BLOCK_FLASH_ATTENTION override is set.
        from .autotune import default_cache

        cached = default_cache().get("flash_attention", (S, h), q.dtype)
        if cached is not None and cached > 0:
            block_size = int(cached)
        # Bigger blocks amortize the online-softmax bookkeeping across more
        # MXU work: 1024 measured 1.5x over 512 from S=4096 up on v5e
        # (75.6 vs 50.6 TF/s at 32k; 31 vs 46 ms at 4k); 2048 exceeds VMEM.
        # _use_resident already cuts over to the blocked path at 4096, so
        # 1024 here never reaches the resident kernels (which cannot
        # compile it). Guard: only when 1024 pads no more than 512 would
        # (S=4608 runs exact at 512; 1024 would add 11% dead work).
        elif S >= 4096 and _round_up(S, 1024) == _round_up(S, 512):
            block_size = 1024
        else:
            block_size = DEFAULT_BLOCK
        if cached is None:
            # Bank the heuristic so the table documents what actually ran
            # (and ATX603 can lint against it).
            default_cache().put("flash_attention", (S, h), q.dtype, block_size)
    block = min(block_size, _round_up(S, 128) if S < block_size else block_size)
    # Pad S up to a block multiple (e.g. the ubiquitous S-1 from next-token
    # shifting). Padded KV columns sit at positions >= S: under causal they
    # are masked for every real row by construction; non-causal kernels mask
    # cols >= valid explicitly. Padded Q rows are sliced away.
    padded = _round_up(S, block)
    if padded != S:
        pad = [(0, 0), (0, padded - S), (0, 0), (0, 0)]
        q = jnp.pad(q, pad)
        k = jnp.pad(k, pad)
        v = jnp.pad(v, pad)
    # kernels run in (B, H, S, h)
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    o = _flash(qt, kt, vt, scale, block, causal, interpret, S, window)
    o = o.transpose(0, 2, 1, 3)
    return o[:, :S] if padded != S else o


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


# Shared block/tuning helpers for the `native/pallas/` kernel tier: every
# tier kernel needs "largest tile that divides this dim" (grids must cover
# exactly — the tier kernels never pad, they fall back) and per-grid
# dimension semantics.

def pick_block(dim: int, candidates: tuple[int, ...] = (512, 256, 128, 64, 32, 16, 8)):
    """Largest candidate evenly dividing ``dim``; ``dim`` itself when smaller
    than every candidate; ``None`` when no candidate divides (caller falls
    back to the reference lowering)."""
    if dim <= 0:
        return None
    for c in candidates:
        if dim >= c and dim % c == 0:
            return c
    if dim < min(candidates):
        return dim
    return None


def tuned_call_kwargs(interpret: bool, semantics: tuple[str, ...]):
    """`pallas_call` kwargs with per-grid dimension semantics, dropped in
    interpret mode and on pallas versions without TPUCompilerParams."""
    kwargs = {"interpret": interpret}
    if not interpret:
        try:
            kwargs["compiler_params"] = pltpu.TPUCompilerParams(
                dimension_semantics=tuple(semantics)
            )
        except Exception:  # pragma: no cover - version dependent
            pass
    return kwargs

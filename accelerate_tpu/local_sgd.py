"""Local SGD: skip cross-replica gradient sync for k steps, then average.

Analog of the reference `local_sgd.py:19-106` (`LocalSGD` context manager:
`no_sync` for ``local_sgd_steps-1`` steps, then `_reduce_model_params`
averages). Under GSPMD the gradient all-reduce is *implicit* — a replicated
parameter tree forces XLA to insert it — so "skipping sync" requires a real
layout change, not a flag:

- each data-parallel replica owns its own parameter/optimizer-state copy,
  materialized as a leading ``[n_replicas]`` axis sharded over the batch
  axes (memory cost on-device is identical to DP, where every device holds
  a full replica anyway);
- the train step `vmap`s the loss/grad/optax update over that axis — XLA
  compiles it with **zero cross-replica collectives**;
- every ``local_sgd_steps``-th step a `lax.cond`-gated mean-and-broadcast
  over the replica axis merges the params (the one collective; the cond
  keeps it out of non-sync steps so ICI/DCN traffic drops by ~k×, which is
  the entire point of Local SGD on slow interconnects).

Optimizer state stays replica-local across merges, matching the reference
(which only all-reduces model params, `local_sgd.py:103-106`).

Usage::

    acc = Accelerator(...)
    state = acc.create_train_state(init_fn, tx)
    state = stack_train_state(state, acc.mesh)
    step = make_local_sgd_step(acc, loss_fn, local_sgd_steps=8)
    for batch in loader:
        state, metrics = step(state, batch)
    state = unstack_train_state(state)   # final merge (reference __exit__)
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec

from .accelerator import TrainState, global_norm
from .parallel.mesh import BATCH_AXES, data_parallel_size


def _stacked_sharding(mesh) -> NamedSharding:
    """Leading replica axis over the batch mesh axes; inner dims replicated
    within a replica (Local SGD is a DP-regime technique)."""
    return NamedSharding(mesh, PartitionSpec(BATCH_AXES))


def _merge_params(params: Any) -> Any:
    """Mean over the replica axis, broadcast back to the stacked layout —
    the single definition of the Local-SGD merge rule."""
    return jax.tree.map(
        lambda x: jnp.broadcast_to(jnp.mean(x, axis=0)[None], x.shape), params
    )


def stack_train_state(state: TrainState, mesh) -> TrainState:
    """Tile params/opt_state with a leading ``[n_replicas]`` axis sharded
    over the batch axes — each replica's copy lives on its own devices."""
    n = data_parallel_size(mesh)
    sharding = _stacked_sharding(mesh)

    def tile_tree(tree):
        # Compile the broadcast with sharded out-shardings so each replica's
        # copy materializes directly on its own devices — an eager broadcast
        # would transiently hold the n-times-sized array on one device.
        shardings = jax.tree.map(lambda _: sharding, tree)
        return jax.jit(
            lambda t: jax.tree.map(
                lambda x: jnp.broadcast_to(x[None], (n,) + jnp.shape(x)), t
            ),
            out_shardings=shardings,
        )(tree)

    return state.replace(
        params=tile_tree(state.params),
        opt_state=tile_tree(state.opt_state),
    )


def unstack_train_state(state: TrainState) -> TrainState:
    """Merge a stacked state back to a single-copy TrainState: params are
    averaged over the replica axis (the reference's exit-time reduce);
    optimizer state takes replica 0's copy."""
    return state.replace(
        params=jax.tree.map(lambda p: jnp.mean(p, axis=0), state.params),
        opt_state=jax.tree.map(lambda o: o[0], state.opt_state),
    )


def sync_params(state: TrainState) -> TrainState:
    """Force a mid-training merge: average params across replicas, keeping
    the stacked layout (all copies identical afterwards)."""
    return state.replace(params=_merge_params(state.params))


def make_local_sgd_step(
    accelerator: Any,
    loss_fn: Callable[..., Any],
    *,
    local_sgd_steps: int = 8,
    has_aux: bool = False,
) -> Callable[[TrainState, Any], tuple[TrainState, dict[str, jax.Array]]]:
    """Compile a Local-SGD train step over a stacked TrainState.

    ``loss_fn(params, batch, rng) -> loss`` exactly as in
    `Accelerator.make_train_step`; the global batch's leading dim must be
    divisible by the number of data-parallel replicas (each replica trains
    on its own contiguous slice — the slice it already holds locally).
    """
    mesh = accelerator.mesh
    n = data_parallel_size(mesh)
    policy = accelerator.policy
    base_rng = accelerator.rng
    max_grad_norm = accelerator.max_grad_norm
    if policy.compute_dtype == jnp.float16:
        raise NotImplementedError(
            "Local SGD with fp16 is not supported: the dynamic loss scaler "
            "would need per-replica state and cross-replica overflow "
            "handling. Use mixed_precision='bf16' (no scaler needed)."
        )
    if accelerator.gradient_accumulation_steps > 1:
        raise NotImplementedError(
            "Local SGD with gradient accumulation is not supported; run more "
            "local steps instead (they serve the same purpose here)."
        )

    def compute_loss(params: Any, batch: Any, rng: jax.Array):
        cparams = policy.cast_for_compute(params)
        cbatch = policy.cast_for_compute(batch)
        out = loss_fn(cparams, cbatch, rng)
        loss, aux = out if has_aux else (out, None)
        return loss.astype(jnp.float32), aux

    grad_fn = jax.value_and_grad(compute_loss, has_aux=True)

    def step_fn(state: TrainState, batch: Any) -> tuple[TrainState, dict[str, jax.Array]]:
        rng = jax.random.fold_in(base_rng, state.step)
        rngs = jax.random.split(rng, n)

        def reshape(x):
            b = x.shape[0]
            if b % n != 0:
                raise ValueError(
                    f"Global batch size {b} is not divisible by the "
                    f"{n} data-parallel replicas Local SGD runs over."
                )
            return x.reshape((n, b // n) + x.shape[1:])

        rbatch = jax.tree.map(reshape, batch)

        def one_replica(params, opt_state, mb, r):
            (loss, _aux), grads = grad_fn(params, mb, r)
            gnorm = global_norm(grads)
            if max_grad_norm is not None:
                clip = jnp.minimum(1.0, max_grad_norm / (gnorm + 1e-6))
                grads = jax.tree.map(lambda g: g * clip, grads)
            updates, new_opt = state.tx.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), new_opt, loss, gnorm

        new_params, new_opt, losses, gnorms = jax.vmap(one_replica)(
            state.params, state.opt_state, rbatch, rngs
        )
        new_step = state.step + 1
        do_sync = (new_step % local_sgd_steps) == 0
        # lax.cond (not where): the replica-axis mean lowers to a collective,
        # and the cond keeps it OFF the program path on non-sync steps.
        new_params = jax.lax.cond(do_sync, _merge_params, lambda p: p, new_params)
        metrics = {"loss": jnp.mean(losses), "synced": do_sync}
        if max_grad_norm is not None:
            metrics["grad_norm"] = jnp.mean(gnorms)
        return (
            state.replace(step=new_step, params=new_params, opt_state=new_opt),
            metrics,
        )

    return jax.jit(step_fn, donate_argnums=(0,))


class LocalSGD:
    """API-parity facade over the functional pieces (reference `LocalSGD`
    context manager, `local_sgd.py:19`): stacks on ``__enter__``, merges on
    ``__exit__``. The state lives on the object because the merge must see
    the final value::

        with LocalSGD(acc, state, loss_fn, local_sgd_steps=8) as lsgd:
            for batch in loader:
                metrics = lsgd.step(batch)
        state = lsgd.state        # merged TrainState
    """

    def __init__(
        self,
        accelerator: Any,
        state: TrainState,
        loss_fn: Callable[..., Any],
        *,
        local_sgd_steps: int = 8,
        enabled: bool = True,
        has_aux: bool = False,
    ) -> None:
        self.accelerator = accelerator
        self.state = state
        self.enabled = enabled
        self.local_sgd_steps = local_sgd_steps
        if enabled:
            self._step = make_local_sgd_step(
                accelerator, loss_fn, local_sgd_steps=local_sgd_steps, has_aux=has_aux
            )
        else:
            self._step = accelerator.make_train_step(loss_fn, has_aux=has_aux)

    def __enter__(self) -> "LocalSGD":
        if self.enabled:
            self.state = stack_train_state(self.state, self.accelerator.mesh)
        return self

    def step(self, batch: Any) -> dict[str, jax.Array]:
        self.state, metrics = self._step(self.state, batch)
        return metrics

    def __exit__(self, *exc: Any) -> None:
        if self.enabled:
            self.state = unstack_train_state(self.state)

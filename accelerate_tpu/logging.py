"""Rank-aware logging.

Analog of the reference `logging.py` (`MultiProcessAdapter` :22,
`get_logger` :85): log lines are emitted only on the main process unless
``main_process_only=False``; ``in_order=True`` emits once per process in rank
order with barriers between ranks.
"""

from __future__ import annotations

import logging
import os
from typing import Any

from .state import ProcessState


class MultiProcessAdapter(logging.LoggerAdapter):
    @staticmethod
    def _should_log(main_process_only: bool) -> bool:
        state = ProcessState()
        return not main_process_only or state.is_main_process

    def log(self, level: int, msg: Any, *args: Any, **kwargs: Any) -> None:
        if not self.isEnabledFor(level):
            return
        main_process_only = kwargs.pop("main_process_only", True)
        in_order = kwargs.pop("in_order", False)
        kwargs.setdefault("stacklevel", 2)

        if not in_order:
            if self._should_log(main_process_only):
                msg, kwargs = self.process(msg, kwargs)
                self.logger.log(level, msg, *args, **kwargs)
            return

        state = ProcessState()
        for i in range(state.num_processes):
            if i == state.process_index:
                msg2, kwargs2 = self.process(msg, dict(kwargs))
                self.logger.log(level, msg2, *args, **kwargs2)
            state.wait_for_everyone()

    def process(self, msg: Any, kwargs: dict) -> tuple[Any, dict]:
        state = ProcessState()
        prefix = f"[rank {state.process_index}] " if state.num_processes > 1 else ""
        return f"{prefix}{msg}", kwargs


def get_logger(name: str, log_level: str | None = None) -> MultiProcessAdapter:
    if log_level is None:
        log_level = os.environ.get("ATX_LOG_LEVEL", None)
    logger = logging.getLogger(name)
    if log_level is not None:
        logger.setLevel(log_level.upper())
    return MultiProcessAdapter(logger, {})

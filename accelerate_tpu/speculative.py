"""Speculative decoding: a small draft model proposes K tokens, the target
model verifies all of them in ONE forward pass.

The reference has no speculative path (its `generate()` is transformers',
reference `big_modeling.py:511`); this is a beyond-parity decode
accelerator that falls straight out of the TPU cost model: single-token
decode is HBM-bandwidth-bound (every step streams all weights for one
token), so a verify pass over K+1 positions costs nearly the same wall
time as one decode step. Each accepted draft token is therefore a decode
step the target never pays for — throughput multiplies by the mean number
of committed tokens per iteration (≈ K·acceptance + 1).

Shape discipline (XLA): K is static; one jitted `spec_step` per iteration
runs the draft loop as a `lax.scan` over K single-token steps plus one
(B, K+1) target verify, with both KV caches donated. Only the per-iteration
commit count syncs to the host — the same host-loop design as
`generation.Generator`, amortized K+1 tokens at a time.

Cache bookkeeping rides the models' shared cache contract
(`{"k","v","length"}`, e.g. `models/llama.py:forward_with_cache`): entries
past ``length`` are never attended (the mask is position-based), so
rejecting draft tokens is just writing a smaller ``length`` back — no data
movement.

Batching: acceptance AND commit are per-row. The caches carry per-row
``length`` cursors (shape (B,) — the model cache contract supports both,
`models/layers.py:cache_write`), so each row commits exactly its own
accepted count every iteration: one unlucky row no longer throttles the
batch to the minimum. Rows that hit EOS or their token budget freeze
(commit 0, cursor pinned) while the rest keep going, and the host loop
stops as soon as every row is frozen — no wasted target forwards after
early termination.

Acceptance diagnostics: BENCH_r05's ``specdecode_accept_rate 0.0`` with a
layer-prefix draft was investigated as a suspected logit/position
misalignment in the accept comparison and CLEARED: at K=1 the engine's
accept rate equals the teacher-forced draft/target argmax-agreement rate,
and draft == target through the external-draft path accepts everything
(tests/test_speculative.py::TestAcceptRateRegression pins both). The 0.0
was draft QUALITY — a 2-layer prefix of random weights shares no
distribution with its 24-layer target — so bench.py now trains a
correlated draft/target pair on a synthetic task before measuring
(`_train_affine_lm`), making the accept rate a property of the mechanism
again.

Guarantees (both tested):
- greedy (``do_sample=False``): output is bit-identical to target-only
  greedy decoding, for ANY draft model;
- sampling: tokens are distributed exactly per the target's (warped)
  distribution — the Leviathan et al. accept/residual scheme with
  ``min(1, p/q)`` acceptance and a ``max(0, p-q)`` residual draw, applied
  after `generation.warp_logits` so temperature/top-k/top-p shape both
  distributions identically.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from .generation import GenerationConfig, warp_logits

__all__ = ["SpeculativeGenerator", "generate_speculative"]

ApplyFn = Callable[[Any, jax.Array, Any], tuple[jax.Array, Any]]


def _probs(logits: jax.Array, config: GenerationConfig) -> jax.Array:
    return jax.nn.softmax(warp_logits(logits, config), axis=-1)


class SpeculativeGenerator:
    """Reusable speculative-decoding harness over two cached forwards.

    ``target_apply``/``draft_apply`` follow the family cache contract
    ``(params, tokens, cache) -> (logits, cache)``;
    ``*_init_cache(batch, max_len)`` build the empty caches. ``params`` is
    the pair ``(target_params, draft_params)`` at call time.
    """

    def __init__(
        self,
        target_apply: ApplyFn,
        target_init_cache: Callable[[int, int], Any],
        draft_apply: ApplyFn,
        draft_init_cache: Callable[[int, int], Any],
        config: GenerationConfig | None = None,
        *,
        draft_tokens: int = 4,
        jit_loop: bool = True,
    ) -> None:
        if draft_tokens < 1:
            raise ValueError(f"draft_tokens must be >= 1, got {draft_tokens}")
        self.config = config or GenerationConfig()
        self.draft_tokens = K = draft_tokens
        self.target_init_cache = target_init_cache
        self.draft_init_cache = draft_init_cache
        config_ = self.config
        eos, pad = config_.eos_token_id, config_.pad_token_id

        def prefill(pt, pd, prompt, t_cache, d_cache, rng):
            """Run the prompt through both models; sample the first token
            from the target (identical to non-speculative prefill)."""
            B = prompt.shape[0]
            t_logits, t_cache = target_apply(pt, prompt, t_cache)
            _, d_cache = draft_apply(pd, prompt, d_cache)
            rng, sub = jax.random.split(rng)
            from .generation import sample_tokens

            first = sample_tokens(t_logits[:, -1, :], sub, config_)
            done = (
                first == eos
                if eos is not None
                else jnp.zeros((B,), bool)
            )
            return first, t_cache, d_cache, rng, done

        def spec_step(pt, pd, last, t_cache, d_cache, rng, done, committed, quota):
            """One draft-K + verify iteration with PER-ROW commits.

            Returns ``tokens`` (B, K+1) with row r's committed tokens in its
            first ``n_row[r]`` columns (the host slices per row), caches
            rolled back to each row's committed length, the EOS state, and
            the per-row committed totals. Rows that are done (EOS) or have
            reached ``quota`` committed tokens are FROZEN: they commit 0 and
            their cache cursors stay put (bounding cache writes to
            ``[len, len+K+1)`` regardless of how long the batch's slowest
            row takes)."""
            B = last.shape[0]
            frozen = done | (committed >= quota)
            rng, r_draft, r_accept, r_fix = jax.random.split(rng, 4)

            # --- draft phase: K+1 single-token steps under lax.scan. Only
            # the first K proposals are verified; the extra step exists so
            # the draft CACHE covers position base+K (reached when all K
            # drafts are accepted) — without it the next iteration would
            # attend an unwritten cache row there.
            def draft_body(carry, r):
                tok, cache = carry
                logits, cache = draft_apply(pd, tok[:, None], cache)
                logits = logits[:, -1, :]
                if config_.do_sample:
                    nxt = jax.random.categorical(
                        r, warp_logits(logits, config_), axis=-1
                    ).astype(jnp.int32)
                else:
                    nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                return (nxt, cache), (nxt, _probs(logits, config_))

            (_, d_cache), (drafted, q_probs) = jax.lax.scan(
                draft_body, (last, d_cache), jax.random.split(r_draft, K + 1)
            )
            drafted = jnp.moveaxis(drafted, 0, 1)[:, :K]  # (B, K)
            q_probs = jnp.moveaxis(q_probs, 0, 1)[:, :K]  # (B, K, V)

            # --- verify phase: ONE target forward over [last, d_1..d_K].
            verify_in = jnp.concatenate([last[:, None], drafted], axis=1)
            t_logits, t_cache = target_apply(pt, verify_in, t_cache)
            p_probs = _probs(t_logits, config_)  # (B, K+1, V)

            # --- acceptance: per-row count of leading drafts that pass.
            if config_.do_sample:
                # Leviathan accept test: u < p(x)/q(x) per drafted token.
                p_at = jnp.take_along_axis(
                    p_probs[:, :K, :], drafted[:, :, None], axis=-1
                )[..., 0]
                q_at = jnp.take_along_axis(q_probs, drafted[:, :, None], axis=-1)[..., 0]
                u = jax.random.uniform(r_accept, (B, K))
                ok = u * q_at < p_at
            else:
                ok = drafted == jnp.argmax(t_logits[:, :K, :], axis=-1)
            accepted = jnp.cumprod(ok.astype(jnp.int32), axis=1)  # still-accepted mask
            a_row = accepted.sum(axis=1)  # (B,) accepted drafts in [0, K]

            # --- the (a+1)-th token, PER ROW: at a == K it's the bonus
            # draw from the target's K-th distribution; at a < K the draft
            # at slot a was rejected, so draw from the residual
            # max(0, p - q) (sampling) / take the target's argmax (greedy).
            a_idx = a_row[:, None, None]
            p_a = jnp.take_along_axis(p_probs, a_idx, axis=1)[:, 0, :]  # (B, V)
            if config_.do_sample:
                q_a = jnp.where(
                    (a_row < K)[:, None],
                    jnp.take_along_axis(
                        q_probs, jnp.minimum(a_row, K - 1)[:, None, None], axis=1
                    )[:, 0, :],
                    jnp.zeros_like(p_a),
                )
                resid = jnp.maximum(p_a - q_a, 0.0)
                resid_sum = resid.sum(axis=-1, keepdims=True)
                # Degenerate p<=q everywhere can't happen with exact math
                # (both sum to 1) but guard the fp32 edge: fall back to p.
                resid = jnp.where(resid_sum > 1e-9, resid / resid_sum, p_a)
                next_tok = jax.random.categorical(
                    r_fix, jnp.log(jnp.maximum(resid, 1e-38)), axis=-1
                ).astype(jnp.int32)
            else:
                next_tok = jnp.argmax(
                    jnp.take_along_axis(t_logits, a_idx, axis=1)[:, 0, :], axis=-1
                ).astype(jnp.int32)

            # --- per-row commit count: the a accepted drafts + next_tok,
            # capped at the row's remaining quota; frozen rows commit 0.
            n_row = jnp.where(
                frozen, 0, jnp.minimum(a_row + 1, jnp.maximum(quota - committed, 0))
            )

            # --- commit buffer: row r holds [d_1..d_a, next_tok] with
            # next_tok in column a_row[r]; the host takes the first n_row[r].
            cols = jnp.arange(K + 1)[None, :]
            buf = jnp.concatenate([drafted, jnp.zeros((B, 1), jnp.int32)], axis=1)
            buf = jnp.where(cols == a_row[:, None], next_tok[:, None], buf)
            committed_mask = cols < n_row[:, None]
            # EOS/pad discipline over each row's committed prefix.
            if eos is not None:
                is_eos = (buf == eos) & committed_mask
                seen = jnp.cumsum(is_eos.astype(jnp.int32), axis=1) - is_eos.astype(jnp.int32)
                dead = done[:, None] | (seen > 0)
                buf = jnp.where(dead & committed_mask, pad, buf)
                done = done | (is_eos & ~dead).any(axis=1)

            # --- roll both caches back to each row's committed length. The
            # verify wrote K+1 entries at the row's base; committed are the
            # first n_row (last + n_row-1 drafts), with `next_tok` pending.
            # Frozen rows stay at base (their writes land in [base, base+K+1)
            # every iteration and are never read).
            base = t_cache["length"] - (K + 1)
            t_cache = dict(t_cache, length=base + n_row)
            d_cache = dict(d_cache, length=base + n_row)
            committed = committed + n_row
            # A row that just committed EOS (or exhausted its quota) is
            # frozen from the next iteration on; keep its pending token
            # stable so the draft input stays a valid id.
            next_tok = jnp.where(done | (committed >= quota), last, next_tok)
            # Observability: PER-ROW acceptance over live rows (what a
            # draft-model choice controls).
            live = ~frozen
            accept_frac = jnp.where(
                live.any(),
                (jnp.where(live, a_row, 0).sum() / jnp.maximum(live.sum(), 1)) / K,
                jnp.asarray(1.0),
            )
            return buf, n_row, next_tok, accept_frac, t_cache, d_cache, rng, done, committed

        if jit_loop:
            prefill = jax.jit(prefill, donate_argnums=(3, 4))
            spec_step = jax.jit(spec_step, donate_argnums=(3, 4))
        self._prefill = prefill
        self._spec_step = spec_step
        self.last_accept_rate = 0.0
        # Iterations whose commits were actually consumed by the last call
        # (excludes trailing over-dispatched ones) — the wall-clock driver
        # for batched decoding: per-row commits make this track the SLOWEST
        # row's own need instead of the min-commit count.
        self.last_iterations = 0

    def __call__(
        self,
        target_params: Any,
        draft_params: Any,
        prompt: jax.Array,
        *,
        rng: jax.Array | None = None,
        max_new_tokens: int | None = None,
        cache_len: int | None = None,
    ) -> jax.Array:
        """(B, S) int32 -> (B, S + max_new_tokens); EOS rows padded.

        ``max_new_tokens`` overrides the config's per call. The jitted
        steps specialize on CACHE SHAPE, which defaults to
        ``S + budget + 2*(K+1)`` — so distinct budgets retrace unless
        ``cache_len`` pins one capacity (any value >= the default bound)
        across calls.

        Also records ``self.last_accept_rate`` (mean drafted-token
        acceptance over the call) for observability/benching."""
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        budget = (
            max_new_tokens if max_new_tokens is not None else self.config.max_new_tokens
        )
        if budget <= 0:
            return prompt
        B, S = prompt.shape
        K = self.draft_tokens
        # Slack: optimistic dispatch (below) can overshoot the budget by at
        # most one iteration's K+1 commits, plus the K+1-wide verify write
        # region past the final committed position.
        needed = S + budget + 2 * (K + 1)
        max_len = cache_len if cache_len is not None else needed
        if max_len < needed:
            raise ValueError(
                f"cache_len={max_len} is too small for prompt {S} + "
                f"max_new_tokens {budget} with draft_tokens {K}; need >= {needed}."
            )
        t_cache = self.target_init_cache(B, max_len)
        d_cache = self.draft_init_cache(B, max_len)
        last, t_cache, d_cache, rng, done = self._prefill(
            target_params, draft_params, prompt, t_cache, d_cache, rng
        )
        # Switch to per-row length cursors AFTER prefill (the model cache
        # contract accepts scalar or (B,)): prefill — the largest KV write
        # of the whole call — keeps the scalar dynamic_update_slice fast
        # path; from here on each row advances by its own commits.
        t_cache = dict(t_cache, length=jnp.broadcast_to(t_cache["length"], (B,)))
        d_cache = dict(d_cache, length=jnp.broadcast_to(d_cache["length"], (B,)))
        # The iteration chain lives on device; the host only needs per-row
        # commit COUNTS (and EOS flags) to know when to stop. A sync per
        # iteration would serialize every step on the host<->device round
        # trip (fatal over a remote tunnel, where one RTT dwarfs the verify
        # itself), so dispatch iterations OPTIMISTICALLY in batches of
        # ceil(remaining / (K+1)) — enough to finish the slowest live row if
        # every draft is accepted — then read the whole batch's counts in
        # one sync. Rejections just trigger another (smaller) batch; the
        # token stream is identical either way. Rows that hit EOS or their
        # budget freeze on device, and the loop ends as soon as no live row
        # remains (no wasted target forwards after early termination).
        quota = budget - 1  # per-row tokens still needed after `first_tok`
        first_tok = last
        committed = jnp.zeros((B,), jnp.int32)
        quota_dev = jnp.asarray(quota, jnp.int32)
        bufs: list[Any] = []  # device (B, K+1) commit buffers, in order
        counts: list[Any] = []  # host (B,) per-iteration commit counts
        accepts: list[float] = []
        totals = np.zeros((B,), np.int64)
        done_h = np.asarray(jax.device_get(done))
        while True:
            live = ~done_h & (totals < quota)
            if not live.any():
                break
            m = -(-int(quota - totals[live].min()) // (K + 1))
            batch_n, batch_af = [], []
            for _ in range(m):
                buf, n, last, accept_frac, t_cache, d_cache, rng, done, committed = (
                    self._spec_step(
                        target_params, draft_params, last, t_cache, d_cache, rng,
                        done, committed, quota_dev,
                    )
                )
                bufs.append(buf)
                batch_n.append(n)
                batch_af.append(accept_frac)
            ns, afs, done_h = jax.device_get(
                (jnp.stack(batch_n), jnp.stack(batch_af), done)
            )
            counts.extend(np.asarray(row) for row in ns)
            accepts.extend(float(v) for v in afs)
            totals += np.asarray(ns).sum(axis=0)
            done_h = np.asarray(done_h)
        # Assemble on host: one pipelined fetch of every commit buffer, then
        # per-row placement at each row's running offset. Rows frozen by EOS
        # underfill their budget; the remainder stays pad (matching the
        # vanilla generator's pad discipline).
        out = np.full((B, quota), self.config.pad_token_id, np.int32)
        pos = np.zeros((B,), np.int64)
        used = 0
        host_bufs = jax.device_get(bufs)
        for hb, n in zip(host_bufs, counts):
            if (pos >= np.minimum(totals, quota)).all():
                break
            for r in range(B):
                take = int(min(n[r], quota - pos[r]))
                if take > 0:
                    out[r, pos[r] : pos[r] + take] = hb[r, :take]
                    pos[r] += take
            used += 1
        self.last_accept_rate = sum(accepts[:used]) / max(used, 1)
        self.last_iterations = used
        first_h = np.asarray(jax.device_get(first_tok))[:, None].astype(np.int32)
        return jnp.concatenate(
            [prompt, jnp.asarray(first_h), jnp.asarray(out)], axis=1
        )


def generate_speculative(
    target_params: Any,
    draft_params: Any,
    prompt: jax.Array,
    *,
    target_apply: ApplyFn,
    target_init_cache: Callable[[int, int], Any],
    draft_apply: ApplyFn,
    draft_init_cache: Callable[[int, int], Any],
    config: GenerationConfig | None = None,
    draft_tokens: int = 4,
    rng: jax.Array | None = None,
    jit_loop: bool = True,
) -> jax.Array:
    """One-shot convenience over `SpeculativeGenerator` (rebuilds the jitted
    steps per call — construct the generator once for repeated use)."""
    gen = SpeculativeGenerator(
        target_apply, target_init_cache, draft_apply, draft_init_cache,
        config, draft_tokens=draft_tokens, jit_loop=jit_loop,
    )
    return gen(target_params, draft_params, prompt, rng=rng)

"""Speculative decoding: a small draft model proposes K tokens, the target
model verifies all of them in ONE forward pass.

The reference has no speculative path (its `generate()` is transformers',
reference `big_modeling.py:511`); this is a beyond-parity decode
accelerator that falls straight out of the TPU cost model: single-token
decode is HBM-bandwidth-bound (every step streams all weights for one
token), so a verify pass over K+1 positions costs nearly the same wall
time as one decode step. Each accepted draft token is therefore a decode
step the target never pays for — throughput multiplies by the mean number
of committed tokens per iteration (≈ K·acceptance + 1).

Shape discipline (XLA): K is static; one jitted `spec_step` per iteration
runs the draft loop as a `lax.scan` over K single-token steps plus one
(B, K+1) target verify, with both KV caches donated. Only the per-iteration
commit count syncs to the host — the same host-loop design as
`generation.Generator`, amortized K+1 tokens at a time.

Cache bookkeeping rides the models' shared cache contract
(`{"k","v","length"}`, e.g. `models/llama.py:forward_with_cache`): entries
past ``length`` are never attended (the mask is position-based), so
rejecting draft tokens is just writing a smaller ``length`` back — no data
movement.

Batching: acceptance is per-row, but the caches share one scalar
``length``, so an iteration commits the MINIMUM accepted count across
rows; rows that accepted more simply re-propose those tokens next
iteration (with fresh randomness — still a valid draw). Throughput
degrades gracefully with batch divergence; the exactness guarantees are
unaffected.

Guarantees (both tested):
- greedy (``do_sample=False``): output is bit-identical to target-only
  greedy decoding, for ANY draft model;
- sampling: tokens are distributed exactly per the target's (warped)
  distribution — the Leviathan et al. accept/residual scheme with
  ``min(1, p/q)`` acceptance and a ``max(0, p-q)`` residual draw, applied
  after `generation.warp_logits` so temperature/top-k/top-p shape both
  distributions identically.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp

from .generation import GenerationConfig, warp_logits

__all__ = ["SpeculativeGenerator", "generate_speculative"]

ApplyFn = Callable[[Any, jax.Array, Any], tuple[jax.Array, Any]]


def _probs(logits: jax.Array, config: GenerationConfig) -> jax.Array:
    return jax.nn.softmax(warp_logits(logits, config), axis=-1)


class SpeculativeGenerator:
    """Reusable speculative-decoding harness over two cached forwards.

    ``target_apply``/``draft_apply`` follow the family cache contract
    ``(params, tokens, cache) -> (logits, cache)``;
    ``*_init_cache(batch, max_len)`` build the empty caches. ``params`` is
    the pair ``(target_params, draft_params)`` at call time.
    """

    def __init__(
        self,
        target_apply: ApplyFn,
        target_init_cache: Callable[[int, int], Any],
        draft_apply: ApplyFn,
        draft_init_cache: Callable[[int, int], Any],
        config: GenerationConfig | None = None,
        *,
        draft_tokens: int = 4,
        jit_loop: bool = True,
    ) -> None:
        if draft_tokens < 1:
            raise ValueError(f"draft_tokens must be >= 1, got {draft_tokens}")
        self.config = config or GenerationConfig()
        self.draft_tokens = K = draft_tokens
        self.target_init_cache = target_init_cache
        self.draft_init_cache = draft_init_cache
        config_ = self.config
        eos, pad = config_.eos_token_id, config_.pad_token_id

        def prefill(pt, pd, prompt, t_cache, d_cache, rng):
            """Run the prompt through both models; sample the first token
            from the target (identical to non-speculative prefill)."""
            t_logits, t_cache = target_apply(pt, prompt, t_cache)
            _, d_cache = draft_apply(pd, prompt, d_cache)
            rng, sub = jax.random.split(rng)
            from .generation import sample_tokens

            first = sample_tokens(t_logits[:, -1, :], sub, config_)
            done = (
                first == eos
                if eos is not None
                else jnp.zeros((prompt.shape[0],), bool)
            )
            return first, t_cache, d_cache, rng, done

        def spec_step(pt, pd, last, t_cache, d_cache, rng, done):
            """One draft-K + verify iteration.

            Returns ``tokens`` (B, K+1) with the committed tokens in the
            first ``n_commit`` columns (the host slices), updated caches
            rolled back to the committed length, and the EOS state."""
            B = last.shape[0]
            rng, r_draft, r_accept, r_fix = jax.random.split(rng, 4)

            # --- draft phase: K+1 single-token steps under lax.scan. Only
            # the first K proposals are verified; the extra step exists so
            # the draft CACHE covers position base+K (reached when all K
            # drafts are accepted) — without it the next iteration would
            # attend an unwritten cache row there.
            def draft_body(carry, r):
                tok, cache = carry
                logits, cache = draft_apply(pd, tok[:, None], cache)
                logits = logits[:, -1, :]
                if config_.do_sample:
                    nxt = jax.random.categorical(
                        r, warp_logits(logits, config_), axis=-1
                    ).astype(jnp.int32)
                else:
                    nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                return (nxt, cache), (nxt, _probs(logits, config_))

            (_, d_cache), (drafted, q_probs) = jax.lax.scan(
                draft_body, (last, d_cache), jax.random.split(r_draft, K + 1)
            )
            drafted = jnp.moveaxis(drafted, 0, 1)[:, :K]  # (B, K)
            q_probs = jnp.moveaxis(q_probs, 0, 1)[:, :K]  # (B, K, V)

            # --- verify phase: ONE target forward over [last, d_1..d_K].
            verify_in = jnp.concatenate([last[:, None], drafted], axis=1)
            t_logits, t_cache = target_apply(pt, verify_in, t_cache)
            p_probs = _probs(t_logits, config_)  # (B, K+1, V)

            # --- acceptance: per-row count of leading drafts that pass.
            if config_.do_sample:
                # Leviathan accept test: u < p(x)/q(x) per drafted token.
                p_at = jnp.take_along_axis(
                    p_probs[:, :K, :], drafted[:, :, None], axis=-1
                )[..., 0]
                q_at = jnp.take_along_axis(q_probs, drafted[:, :, None], axis=-1)[..., 0]
                u = jax.random.uniform(r_accept, (B, K))
                ok = u * q_at < p_at
            else:
                ok = drafted == jnp.argmax(t_logits[:, :K, :], axis=-1)
            accepted = jnp.cumprod(ok.astype(jnp.int32), axis=1)  # still-accepted mask
            a_raw = accepted.sum(axis=1)  # (B,) in [0, K]
            # Finished rows must not throttle the shared commit count.
            a_row = jnp.where(done, K, a_raw)
            a = jnp.min(a_row)  # scalar commit length for this iteration

            # --- the (a+1)-th token: accepted rows take their next draft
            # (greedy: equals the target argmax; sampling: it passed the
            # accept test), rejected-at-a rows draw from the residual
            # max(0, p - q) (sampling) / take the target's token (greedy).
            p_a = jnp.take_along_axis(
                p_probs, jnp.broadcast_to(a, (B,))[:, None, None], axis=1
            )[:, 0, :]  # (B, V) target dist at the first uncommitted slot
            if config_.do_sample:
                # Residual only exists where a draft was rejected (a < K);
                # at a == K this is the plain bonus draw from p_K.
                q_a = jnp.where(
                    (a < K),
                    jnp.take_along_axis(
                        q_probs,
                        jnp.broadcast_to(jnp.minimum(a, K - 1), (B,))[:, None, None],
                        axis=1,
                    )[:, 0, :],
                    jnp.zeros_like(p_a),
                )
                resid = jnp.maximum(p_a - q_a, 0.0)
                resid_sum = resid.sum(axis=-1, keepdims=True)
                # Degenerate p<=q everywhere can't happen with exact math
                # (both sum to 1) but guard the fp32 edge: fall back to p.
                resid = jnp.where(resid_sum > 1e-9, resid / resid_sum, p_a)
                fix = jax.random.categorical(
                    r_fix, jnp.log(jnp.maximum(resid, 1e-38)), axis=-1
                ).astype(jnp.int32)
            else:
                fix = jnp.argmax(
                    jnp.take_along_axis(
                        t_logits, jnp.broadcast_to(a, (B,))[:, None, None], axis=1
                    )[:, 0, :],
                    axis=-1,
                ).astype(jnp.int32)
            row_accepted_past_a = a_row > a
            next_tok = jnp.where(
                row_accepted_past_a,
                jnp.take_along_axis(
                    drafted, jnp.minimum(a, K - 1)[None].repeat(B)[:, None], axis=1
                )[:, 0],
                fix,
            )

            # --- commit buffer: [d_1..d_a, next_tok] in columns 0..a.
            cols = jnp.arange(K + 1)
            buf = jnp.concatenate([drafted, jnp.zeros((B, 1), jnp.int32)], axis=1)
            buf = jnp.where(cols[None, :] == a, next_tok[:, None], buf)
            # EOS/pad discipline over the committed prefix.
            if eos is not None:
                committed_mask = cols[None, :] <= a
                is_eos = (buf == eos) & committed_mask
                seen = jnp.cumsum(is_eos.astype(jnp.int32), axis=1) - is_eos.astype(jnp.int32)
                dead = done[:, None] | (seen > 0)
                buf = jnp.where(dead & committed_mask, pad, buf)
                done = done | (is_eos & ~dead).any(axis=1)
                next_tok = buf[jnp.arange(B), jnp.broadcast_to(a, (B,))]

            # --- roll both caches back to the committed length. The verify
            # wrote K+1 entries; committed are the first a+1 (last + a
            # drafts), with `next_tok` pending for the next iteration.
            base = t_cache["length"] - (K + 1)
            t_cache = dict(t_cache, length=base + 1 + a)
            d_cache = dict(d_cache, length=base + 1 + a)
            # Observability: PER-ROW acceptance (not the min-commit count —
            # with large divergent batches the min is pessimistic while
            # per-row acceptance is what a draft-model choice controls).
            live = ~done
            accept_frac = jnp.where(
                live.any(),
                (jnp.where(live, a_raw, 0).sum() / jnp.maximum(live.sum(), 1)) / K,
                jnp.asarray(1.0),
            )
            return buf, a + 1, next_tok, accept_frac, t_cache, d_cache, rng, done

        if jit_loop:
            prefill = jax.jit(prefill, donate_argnums=(3, 4))
            spec_step = jax.jit(spec_step, donate_argnums=(3, 4))
        self._prefill = prefill
        self._spec_step = spec_step
        self.last_accept_rate = 0.0

    def __call__(
        self,
        target_params: Any,
        draft_params: Any,
        prompt: jax.Array,
        *,
        rng: jax.Array | None = None,
        max_new_tokens: int | None = None,
        cache_len: int | None = None,
    ) -> jax.Array:
        """(B, S) int32 -> (B, S + max_new_tokens); EOS rows padded.

        ``max_new_tokens`` overrides the config's per call. The jitted
        steps specialize on CACHE SHAPE, which defaults to
        ``S + budget + 2*(K+1)`` — so distinct budgets retrace unless
        ``cache_len`` pins one capacity (any value >= the default bound)
        across calls.

        Also records ``self.last_accept_rate`` (mean drafted-token
        acceptance over the call) for observability/benching."""
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        budget = (
            max_new_tokens if max_new_tokens is not None else self.config.max_new_tokens
        )
        if budget <= 0:
            return prompt
        B, S = prompt.shape
        K = self.draft_tokens
        # Slack: optimistic dispatch (below) can overshoot the budget by at
        # most one iteration's K+1 commits, plus the K+1-wide verify write
        # region past the final committed position.
        needed = S + budget + 2 * (K + 1)
        max_len = cache_len if cache_len is not None else needed
        if max_len < needed:
            raise ValueError(
                f"cache_len={max_len} is too small for prompt {S} + "
                f"max_new_tokens {budget} with draft_tokens {K}; need >= {needed}."
            )
        t_cache = self.target_init_cache(B, max_len)
        d_cache = self.draft_init_cache(B, max_len)
        last, t_cache, d_cache, rng, done = self._prefill(
            target_params, draft_params, prompt, t_cache, d_cache, rng
        )
        # The iteration chain lives on device; the host only needs commit
        # COUNTS to know when to stop. A sync per iteration would serialize
        # every step on the host<->device round trip (fatal over a remote
        # tunnel, where one RTT dwarfs the verify itself), so dispatch
        # iterations OPTIMISTICALLY in batches of ceil(remaining / (K+1)) —
        # enough to finish if every draft is accepted — then read the whole
        # batch's counts in one sync. Rejections just trigger another
        # (smaller) batch; the token stream is identical either way.
        first_tok = last
        bufs: list[Any] = []  # device (B, K+1) commit buffers, in order
        counts: list[int] = []
        accepts: list[float] = []
        got = 1
        while got < budget:
            m = -(-(budget - got) // (K + 1))
            batch = []
            for _ in range(m):
                buf, n, last, accept_frac, t_cache, d_cache, rng, done = (
                    self._spec_step(
                        target_params, draft_params, last, t_cache, d_cache, rng, done
                    )
                )
                bufs.append(buf)
                batch.append((n, accept_frac))
            ns, afs = jax.device_get(
                (jnp.stack([b[0] for b in batch]), jnp.stack([b[1] for b in batch]))
            )
            counts.extend(int(v) for v in ns)
            accepts.extend(float(v) for v in afs)
            got = 1 + sum(counts)
        # Assemble on host: one pipelined fetch of every commit buffer, then
        # slice each to its committed width (trailing over-dispatched
        # iterations may go entirely unused).
        pieces = [jax.device_get(first_tok)[:, None]]
        host_bufs = jax.device_get(bufs)
        remaining = budget - 1
        used = 0
        for hb, n in zip(host_bufs, counts):
            if remaining <= 0:
                break
            take = min(n, remaining)
            pieces.append(hb[:, :take])
            remaining -= take
            used += 1
        self.last_accept_rate = sum(accepts[:used]) / max(used, 1)
        return jnp.concatenate([prompt] + [jnp.asarray(t) for t in pieces], axis=1)


def generate_speculative(
    target_params: Any,
    draft_params: Any,
    prompt: jax.Array,
    *,
    target_apply: ApplyFn,
    target_init_cache: Callable[[int, int], Any],
    draft_apply: ApplyFn,
    draft_init_cache: Callable[[int, int], Any],
    config: GenerationConfig | None = None,
    draft_tokens: int = 4,
    rng: jax.Array | None = None,
    jit_loop: bool = True,
) -> jax.Array:
    """One-shot convenience over `SpeculativeGenerator` (rebuilds the jitted
    steps per call — construct the generator once for repeated use)."""
    gen = SpeculativeGenerator(
        target_apply, target_init_cache, draft_apply, draft_init_cache,
        config, draft_tokens=draft_tokens, jit_loop=jit_loop,
    )
    return gen(target_params, draft_params, prompt, rng=rng)

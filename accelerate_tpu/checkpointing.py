"""Checkpoint / resume.

TPU-native redesign of the reference checkpoint stack (`accelerator.py:3106`
`save_state` / :3272 `load_state`, `checkpointing.py:57`, FSDP sharded dicts
`utils/fsdp_utils.py:66-246`, merge tool :247-329). Design:

- **Sharded-by-construction**: every process writes only the addressable
  shards it owns (replica 0 of each), so a multi-host FSDP checkpoint never
  materializes a full array anywhere — the analog of torch.distributed
  .checkpoint's SHARDED_STATE_DICT, but it is the *only* format: one layout
  serves save/load on any mesh because load reassembles requested slices
  from overlapping saved shards.
- **Topology-independent load**: save on a (data=2, fsdp=4) mesh, load on
  (fsdp=8) or a single device — the reader slices what each target device
  needs from the shard files (reference FULL↔SHARDED conversion collapses).
- **Plain formats**: one `.npz` per process + one JSON index per process.
  No tensorstore; readers cache decoded shards across slice requests.
- Round-trip state beyond params mirrors the reference: RNG bundle, step,
  dataloader iterator states, and `register_for_checkpointing` objects
  (`checkpointing.py:101-171`, `accelerator.py:3550`).
- `automatic_checkpoint_naming` + `total_limit` rotation
  (`ProjectConfiguration`, reference `utils/dataclasses.py:857-917`).
- Async save: device->host transfer happens synchronously (cheap, HBM->RAM),
  file writing on a background thread (the orbax async-checkpoint pattern).
- **Atomic commit protocol** (`resilience/commit.py`, docs/fault_tolerance.md):
  `save_state` writes into `<dir>.tmp/`, hashes every file into a per-process
  SHA-256 manifest, barriers, then process 0 renames to final and writes a
  `COMMIT` marker last; rotation deletes old checkpoints only AFTER the new
  commit lands, and `load_state(resume="latest")` only ever trusts a
  committed, manifest-verified checkpoint (falling back to the previous one
  on corruption). A kill -9 at any instant is recoverable.
"""

from __future__ import annotations

import atexit
import contextlib
import hashlib
import io
import json
import logging
import os
import pickle
import random as _py_random
import re
import shutil
import struct
import threading
import time
import warnings
import zlib
from typing import TYPE_CHECKING, Any, Callable, Iterable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from .parallel import mesh as _mesh
from .resilience import commit as _commit
from .resilience import replicate as _replicate
from .resilience.commit import (
    CheckpointIntegrityWarning,
    CheckpointShardCoverageError,
    fault_point as _fault_point,
)
from .utils.environment import get_int_from_env

if TYPE_CHECKING:  # pragma: no cover
    from .accelerator import Accelerator, TrainState

logger = logging.getLogger(__name__)

MODEL_DIR = "train_state"
SHARDS_FILE = "shards_{proc}.npz"
INDEX_FILE = "index_{proc}.json"
RNG_FILE = "rng_state_{proc}.json"
DATALOADER_FILE = "dataloaders.json"
CUSTOM_FILE = "custom_checkpoint_{i}.pkl"
METADATA_FILE = "metadata.json"
_CKPT_PATTERN = re.compile(r"^checkpoint_(\d+)$")


# ------------------------------------------------------------------ pytree IO
def _leaf_key(path: tuple) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        elif hasattr(p, "name"):
            parts.append(str(p.name))
        else:
            parts.append(str(p))
    return "/".join(parts)


def _shard_entry_key(leaf_key: str, starts: tuple[int, ...]) -> str:
    return f"{leaf_key}|{','.join(map(str, starts))}"


def _serialize_spec(sharding: Any) -> list | None:
    """JSON-serializable PartitionSpec (None | axis name | list of names per
    dim) for a NamedSharding, or None when the sharding carries no spec.
    Recorded per leaf in the index so an elastic restore knows how each
    array was laid out at save time (diagnostics + future layout planning);
    the restore itself re-lays onto the TARGET's current shardings."""
    spec = getattr(sharding, "spec", None)
    if spec is None:
        return None
    out: list = []
    for entry in spec:
        if entry is None or entry is PartitionSpec.UNCONSTRAINED:
            out.append(None)
        elif isinstance(entry, str):
            out.append(entry)
        else:
            out.append(list(entry))
    return out


def save_pytree(tree: Any, directory: str, *, process_index: int | None = None) -> None:
    """Write the addressable (replica-0) shards of a pytree of jax.Arrays
    (or pre-snapshotted `_HostShardSnapshot` leaves — the async path).

    Layout: ``shards_{proc}.npz`` (shard data) + ``index_{proc}.json``
    (per-leaf global shape/dtype + shard table). Small host-side leaves
    (python/numpy scalars) go straight into the index.
    """
    proc = jax.process_index() if process_index is None else process_index
    os.makedirs(directory, exist_ok=True)
    flat, _ = jax.tree_util.tree_flatten_with_path(
        tree, is_leaf=lambda x: isinstance(x, _HostShardSnapshot)
    )
    shard_data: dict[str, np.ndarray] = {}
    index: dict[str, Any] = {}
    for path, leaf in flat:
        key = _leaf_key(path)
        if isinstance(leaf, jax.Array):
            leaf = _HostShardSnapshot(leaf, process_index=proc)
        if isinstance(leaf, _HostShardSnapshot):
            entry: dict[str, Any] = {
                "shape": list(leaf.shape),
                "dtype": str(leaf.dtype),
                "shards": [],
            }
            if leaf.spec is not None:
                entry["spec"] = leaf.spec
            for starts, data in leaf.shards:
                shard_data[_shard_entry_key(key, starts)] = data
                entry["shards"].append({"starts": list(starts), "shape": list(data.shape)})
            if entry["shards"]:
                index[key] = entry
        else:
            if proc == 0:
                index[key] = {"value": _to_jsonable(leaf)}
    np.savez(os.path.join(directory, SHARDS_FILE.format(proc=proc)), **shard_data)
    with open(os.path.join(directory, INDEX_FILE.format(proc=proc)), "w") as f:
        json.dump(index, f)


def _to_jsonable(leaf: Any) -> Any:
    if isinstance(leaf, (np.integer,)):
        return int(leaf)
    if isinstance(leaf, (np.floating,)):
        return float(leaf)
    if isinstance(leaf, np.ndarray):
        return {"__ndarray__": leaf.tolist(), "dtype": str(leaf.dtype)}
    return leaf


def _from_jsonable(value: Any) -> Any:
    if isinstance(value, dict) and "__ndarray__" in value:
        return np.asarray(value["__ndarray__"], dtype=value["dtype"])
    return value


def _assemble_slice(
    entries: Iterable[tuple[tuple[int, ...], tuple[int, ...], Callable[[], np.ndarray]]],
    idx: tuple[slice, ...],
    shape: tuple[int, ...],
    dtype: Any,
    key: str,
    *,
    context: str = "",
) -> np.ndarray:
    """Source-agnostic shard assembly: build the requested global slice of a
    ``shape``-shaped array from overlapping shards (saved and requested shard
    boundaries need not match).

    ``entries`` is ``(starts, shard_shape, fetch)`` where ``fetch()`` returns
    the shard's data — a decoded npz member, a live device shard, or a
    byte-range fetch from an object store. Entries are consulted in order and
    a shard whose region is already fully covered is SKIPPED WITHOUT
    FETCHING, so expensive sources (peer files, remote byte ranges) listed
    after cheap ones (live local shards) only pay for actual holes.
    """
    req_starts = tuple((sl.start or 0) for sl in idx)
    req_stops = tuple(
        (sl.stop if sl.stop is not None else dim) for sl, dim in zip(idx, shape)
    )
    req_shape = tuple(b - a for a, b in zip(req_starts, req_stops))
    out = np.empty(req_shape, dtype=dtype)
    # Boolean fill mask (not a volume count): overlapping shards must not
    # be able to mask a hole and leak uninitialized memory.
    covered = np.zeros(req_shape, dtype=bool) if req_shape else np.zeros((), dtype=bool)
    for starts, sshape, fetch in entries:
        stops = tuple(a + s for a, s in zip(starts, sshape))
        inter_start = tuple(max(a, b) for a, b in zip(starts, req_starts))
        inter_stop = tuple(min(a, b) for a, b in zip(stops, req_stops))
        if any(a >= b for a, b in zip(inter_start, inter_stop)):
            continue
        dst_idx = tuple(
            slice(a - r0, b - r0) for a, b, r0 in zip(inter_start, inter_stop, req_starts)
        )
        if covered[dst_idx].all():
            continue
        src = fetch()
        src_idx = tuple(
            slice(a - s0, b - s0) for a, b, s0 in zip(inter_start, inter_stop, starts)
        )
        out[dst_idx] = src[src_idx]
        covered[dst_idx] = True
    if not covered.all():
        raise CheckpointShardCoverageError(
            f"Checkpoint shards for {key!r} do not cover requested slice {idx} "
            f"({int(covered.sum())}/{int(np.prod(req_shape))} elements covered) "
            + context
        )
    return out


class _ShardReader:
    """Lazily-opened view over every process's shard files in a directory.

    ``remote`` optionally maps procs whose files are NOT in the directory to
    ``(store, npz_key, index)`` refs from `_ensure_shard_coverage`: their
    shard members are fetched by byte range (`read_npz_member`) straight
    from the replicate store — nothing is downloaded into the committed
    directory. A remote ref wins over a partial local copy (coverage only
    hands out refs for procs whose local index+shards pair is incomplete,
    e.g. debris from an interrupted whole-file fetch).
    """

    def __init__(
        self,
        directory: str,
        remote: dict[int, tuple[Any, str, dict]] | None = None,
    ) -> None:
        self.directory = directory
        self.index: dict[str, Any] = {}
        # leaf key -> list of (starts, shape, proc)
        self.shard_table: dict[str, list[tuple[tuple[int, ...], tuple[int, ...], int]]] = {}
        self._files: dict[int, Any] = {}
        self._array_cache: dict[tuple[int, str], np.ndarray] = {}
        self._remote: dict[int, tuple[Any, str]] = {}
        self._remote_entries: dict[int, dict[str, tuple[int, int, int]]] = {}
        remote = remote or {}
        procs = []
        for name in sorted(os.listdir(directory)):
            m = re.match(r"^index_(\d+)\.json$", name)
            if not m:
                continue
            proc = int(m.group(1))
            if proc in remote:
                continue
            procs.append(proc)
            with open(os.path.join(directory, name)) as f:
                idx = json.load(f)
            self._merge_index(idx, proc)
        for proc, (store, npz_key, idx) in sorted(remote.items()):
            procs.append(proc)
            self._remote[proc] = (store, npz_key)
            self._merge_index(idx, proc)
        if not procs:
            raise FileNotFoundError(f"No checkpoint index files in {directory}")

    def _merge_index(self, idx: dict, proc: int) -> None:
        for key, entry in idx.items():
            if "shards" in entry:
                base = self.index.setdefault(key, {k: entry[k] for k in ("shape", "dtype")})
                base.setdefault("shards", True)
                for sh in entry["shards"]:
                    self.shard_table.setdefault(key, []).append(
                        (tuple(sh["starts"]), tuple(sh["shape"]), proc)
                    )
            else:
                self.index.setdefault(key, entry)

    def _npz(self, proc: int) -> Any:
        if proc not in self._files:
            self._files[proc] = np.load(os.path.join(self.directory, f"shards_{proc}.npz"))
        return self._files[proc]

    def _remote_member(self, proc: int, skey: str) -> np.ndarray:
        store, npz_key = self._remote[proc]
        try:
            entries = self._remote_entries.get(proc)
            if entries is None:
                entries = self._remote_entries[proc] = _zip_entries(store, npz_key)
            arr = read_npz_member(store, npz_key, skey, entries=entries)
        except Exception as e:
            # Anything wrong with the remote copy (corrupt archive, store
            # error) must surface as a coverage failure so resume="latest"
            # falls back to the previous committed checkpoint instead of
            # resuming on a partial reshard.
            raise CheckpointShardCoverageError(
                f"ranged read of shard {skey!r} from {npz_key!r} failed: {e}"
            ) from e
        _fault_point("restore.peer_slice_fetched")
        return arr

    def _shard_array(self, proc: int, skey: str) -> np.ndarray:
        # NpzFile re-reads the zip member on every access; resharding loads
        # touch the same shard once per target device, so cache decoded arrays.
        cached = self._array_cache.get((proc, skey))
        if cached is None:
            if proc in self._remote:
                cached = self._remote_member(proc, skey)
            else:
                cached = self._npz(proc)[skey]
            self._array_cache[(proc, skey)] = cached
        return cached

    def leaf_info(self, key: str) -> dict[str, Any]:
        return self.index[key]

    def read_slice(self, key: str, idx: tuple[slice, ...], shape: tuple[int, ...], dtype: Any) -> np.ndarray:
        """Assemble the requested global slice from overlapping saved shards
        (saved and requested shard boundaries need not match)."""
        entries = [
            (
                starts,
                sshape,
                lambda p=proc, s=_shard_entry_key(key, starts): self._shard_array(p, s),
            )
            for starts, sshape, proc in self.shard_table.get(key, ())
        ]
        return _assemble_slice(
            entries,
            idx,
            shape,
            dtype,
            key,
            context=(
                "— a shard file another process wrote is missing from this "
                "directory (per-node checkpoint restored at a different "
                "topology without a replicate store, or deleted shard files)"
            ),
        )

    def read_full(self, key: str) -> np.ndarray:
        info = self.index[key]
        shape = tuple(info["shape"])
        return self.read_slice(
            key, tuple(slice(0, d) for d in shape), shape, np.dtype(info["dtype"])
        )

    def close(self) -> None:
        for f in self._files.values():
            f.close()
        self._files.clear()
        self._array_cache.clear()


def _index_has_prefix(directory: str, prefix: str) -> bool:
    """Does any leaf key in the checkpoint's merged index start with ``prefix``?
    (Cheap: reads only the JSON index files, no shard data.)"""
    if not os.path.isdir(directory):
        return False
    for name in os.listdir(directory):
        if not re.match(r"^index_(\d+)\.json$", name):
            continue
        with open(os.path.join(directory, name)) as f:
            if any(key.startswith(prefix) for key in json.load(f)):
                return True
    return False


def load_pytree(target: Any, directory: str, remote_shards: dict | None = None) -> Any:
    """Restore a pytree saved with `save_pytree` into ``target``'s structure.

    jax.Array leaves are rebuilt with their **current** shardings (each device
    fetches exactly its slice — topology-independent resharding); other
    leaves come from the JSON index. Raises KeyError on missing leaves.
    ``remote_shards`` (from `_ensure_shard_coverage`) maps procs whose shard
    files are not local to ranged-read refs into the replicate store.
    """
    reader = _ShardReader(directory, remote=remote_shards)
    flat, treedef = jax.tree_util.tree_flatten_with_path(target)
    out = []
    try:
        for path, leaf in flat:
            key = _leaf_key(path)
            if key not in reader.index:
                raise KeyError(
                    f"Leaf {key!r} missing from checkpoint at {directory} "
                    f"(has {len(reader.index)} leaves)"
                )
            info = reader.leaf_info(key)
            if "value" in info:
                out.append(_from_jsonable(info["value"]))
                continue
            shape = tuple(info["shape"])
            dtype = np.dtype(info["dtype"])
            if isinstance(leaf, jax.Array):
                if tuple(leaf.shape) != shape:
                    raise ValueError(
                        f"Shape mismatch for {key!r}: target {tuple(leaf.shape)} vs "
                        f"checkpoint {shape}"
                    )
                target_dtype = leaf.dtype
                if not getattr(leaf, "committed", True):
                    # An uncommitted target (e.g. the scalar `step` from
                    # jnp.zeros) must restore uncommitted: rebuilding it via
                    # make_array_from_callback would COMMIT it to its current
                    # device, and a later jit over committed mesh-sharded
                    # params + a device-0-committed scalar is an error.
                    out.append(
                        jnp.asarray(reader.read_full(key).astype(target_dtype))
                    )
                    continue
                sharding = leaf.sharding
                arr = jax.make_array_from_callback(
                    shape,
                    sharding,
                    lambda idx, k=key, s=shape, d=dtype, td=target_dtype: reader.read_slice(
                        k, idx, s, d
                    ).astype(td),
                )
                out.append(arr)
            else:
                out.append(reader.read_full(key))
    finally:
        reader.close()
    return jax.tree_util.tree_unflatten(treedef, [x for x in out])


# ------------------------------------------------- in-memory resharder (elastic)
# Shrink/grow-in-place (resilience/elastic.py) reuses the shard-assembly
# machinery above on LIVE arrays: survivors rebuild every leaf for a new
# mesh from the shards they already hold in memory, consulting a committed
# remote checkpoint only for slices nobody holds. The sources below all
# speak the same `(starts, shard_shape, fetch)` protocol `_assemble_slice`
# consumes, so the resharder is agnostic to where bytes come from.


class InMemoryShardSource:
    """Live local shards of a pytree, snapshot to host.

    The primary source for the in-place reshard. Unlike the save path
    (replica-0 shards only — every byte written exactly once), this keeps
    ALL addressable shards: replicas are free extra coverage when the
    process that owned replica 0 of a slice is the one that died.
    """

    def __init__(self) -> None:
        self._info: dict[str, dict[str, Any]] = {}
        self._shards: dict[str, list[tuple[tuple[int, ...], np.ndarray]]] = {}

    @classmethod
    def from_tree(cls, tree: Any) -> "InMemoryShardSource":
        src = cls()
        flat, _ = jax.tree_util.tree_flatten_with_path(tree)
        for path, leaf in flat:
            key = _leaf_key(path)
            if isinstance(leaf, jax.Array):
                shards: list[tuple[tuple[int, ...], np.ndarray]] = []
                for shard in leaf.addressable_shards:
                    starts = tuple(
                        (sl.start or 0) for sl in shard.index
                    ) if leaf.ndim else ()
                    shards.append((starts, np.asarray(shard.data)))
                src._info[key] = {
                    "shape": list(leaf.shape),
                    "dtype": str(np.dtype(leaf.dtype)),
                }
                src._shards[key] = shards
            else:
                src._info[key] = {"value": _to_jsonable(leaf)}
        return src

    def leaf_info(self, key: str) -> dict[str, Any] | None:
        return self._info.get(key)

    def shards(
        self, key: str
    ) -> list[tuple[tuple[int, ...], tuple[int, ...], Callable[[], np.ndarray]]]:
        return [
            (starts, tuple(data.shape), lambda d=data: d)
            for starts, data in self._shards.get(key, ())
        ]


def _zip_entries(store: Any, key: str) -> dict[str, tuple[int, int, int]]:
    """Member table of a remote zip (npz) via ranged reads only:
    ``name -> (compress_method, compressed_size, local_header_offset)``.

    Reads the EOCD from a bounded tail fetch, then the central directory —
    two small ranged requests against an arbitrarily large archive."""
    st = store.stat(key)
    if st is None:
        raise _replicate.ObjectStoreError(f"no object {key!r}")
    size = int(st.size)
    # EOCD is 22 bytes + up to 64KiB of archive comment.
    tail_len = min(size, 22 + 65535)
    tail_off = size - tail_len
    tail = store.get_range(key, tail_off, tail_len)
    eocd = tail.rfind(b"PK\x05\x06")
    if eocd < 0:
        raise ValueError(f"{key!r}: no zip end-of-central-directory record")
    cd_size, cd_offset = struct.unpack("<II", tail[eocd + 12 : eocd + 20])
    if cd_offset >= tail_off:
        cd = tail[cd_offset - tail_off : cd_offset - tail_off + cd_size]
    else:
        cd = store.get_range(key, cd_offset, cd_size)
    entries: dict[str, tuple[int, int, int]] = {}
    pos = 0
    while pos + 46 <= len(cd) and cd[pos : pos + 4] == b"PK\x01\x02":
        (method,) = struct.unpack("<H", cd[pos + 10 : pos + 12])
        comp_size, _uncomp = struct.unpack("<II", cd[pos + 20 : pos + 28])
        name_len, extra_len, comment_len = struct.unpack(
            "<HHH", cd[pos + 28 : pos + 34]
        )
        (header_off,) = struct.unpack("<I", cd[pos + 42 : pos + 46])
        name = cd[pos + 46 : pos + 46 + name_len].decode("utf-8")
        entries[name] = (method, comp_size, header_off)
        pos += 46 + name_len + extra_len + comment_len
    return entries


def read_npz_member(
    store: Any,
    key: str,
    member: str,
    *,
    entries: dict[str, tuple[int, int, int]] | None = None,
) -> np.ndarray:
    """One array out of a remote ``.npz`` by byte range (`ObjectStore.
    get_range`) — the member's local header + payload only, never the whole
    archive. np.savez stores members uncompressed (ZIP_STORED), so a member
    IS a contiguous byte range; compressed members are handled anyway.
    Pass ``entries`` (from `_zip_entries`) to amortize the directory reads
    across members of the same archive."""
    if entries is None:
        entries = _zip_entries(store, key)
    name = member if member in entries else member + ".npy"
    if name not in entries:
        raise KeyError(f"{member!r} not in {key!r} ({len(entries)} members)")
    method, comp_size, header_off = entries[name]
    # 30-byte local file header carries its own (possibly longer) extra field.
    header = store.get_range(key, header_off, 30)
    if header[:4] != b"PK\x03\x04":
        raise ValueError(f"{key!r}: bad local header for member {name!r}")
    name_len, extra_len = struct.unpack("<HH", header[26:30])
    data = store.get_range(key, header_off + 30 + name_len + extra_len, comp_size)
    if method == 8:
        data = zlib.decompress(data, -15)
    elif method != 0:
        raise ValueError(f"{key!r}: unsupported zip method {method} for {name!r}")
    return np.load(io.BytesIO(data), allow_pickle=False)


class StoreShardSource:
    """Shards of a committed remote checkpoint, fetched by byte range.

    The fallback source for the in-place reshard: survivors' live shards are
    consulted first, and thanks to `_assemble_slice`'s covered-region skip a
    fetch here only fires for slices nobody alive holds — and downloads only
    that member's bytes, not the whole ``shards_<p>.npz`` (the ROADMAP
    "streams whole npz files" follow-up). Fires the
    ``shrink.peer_slice_fetched`` fault point per fetched member."""

    def __init__(self, store: Any, name: str, procs: Iterable[int]) -> None:
        self.store = store
        self.name = name
        self._info: dict[str, dict[str, Any]] = {}
        # leaf key -> [(starts, shape, proc)]
        self._table: dict[str, list[tuple[tuple[int, ...], tuple[int, ...], int]]] = {}
        self._prefix: dict[int, str] = {}
        self._entries: dict[int, dict[str, tuple[int, int, int]]] = {}
        self._cache: dict[tuple[int, str], np.ndarray] = {}
        for p in procs:
            for prefix in (f"node_{p}/{name}", name):
                idx_key = f"{prefix}/{MODEL_DIR}/{INDEX_FILE.format(proc=p)}"
                if not store.exists(idx_key):
                    continue
                idx = json.loads(store.get_bytes(idx_key).decode())
                self._prefix[p] = prefix
                for key, entry in idx.items():
                    if "shards" in entry:
                        self._info.setdefault(
                            key, {k: entry[k] for k in ("shape", "dtype")}
                        )
                        for sh in entry["shards"]:
                            self._table.setdefault(key, []).append(
                                (tuple(sh["starts"]), tuple(sh["shape"]), p)
                            )
                    else:
                        self._info.setdefault(key, entry)
                break

    @property
    def procs(self) -> list[int]:
        return sorted(self._prefix)

    def leaf_info(self, key: str) -> dict[str, Any] | None:
        return self._info.get(key)

    def _fetch(self, proc: int, skey: str) -> np.ndarray:
        cached = self._cache.get((proc, skey))
        if cached is not None:
            return cached
        npz_key = f"{self._prefix[proc]}/{MODEL_DIR}/{SHARDS_FILE.format(proc=proc)}"
        entries = self._entries.get(proc)
        if entries is None:
            entries = self._entries[proc] = _zip_entries(self.store, npz_key)
        arr = read_npz_member(self.store, npz_key, skey, entries=entries)
        _fault_point("shrink.peer_slice_fetched")
        self._cache[(proc, skey)] = arr
        return arr

    def shards(
        self, key: str
    ) -> list[tuple[tuple[int, ...], tuple[int, ...], Callable[[], np.ndarray]]]:
        return [
            (starts, sshape, lambda p=proc, s=_shard_entry_key(key, starts): self._fetch(p, s))
            for starts, sshape, proc in self._table.get(key, ())
        ]


def store_fallback_source(store: Any, expected_step: int) -> StoreShardSource | None:
    """Newest remote *committed* checkpoint whose saved ``step`` equals
    ``expected_step``, as a `StoreShardSource` — None when the store has no
    same-step commit. The step gate is load-bearing: mixing a stale commit's
    shards into a live reshard would silently roll back part of the state.
    The step probe itself is a ~100-byte ranged read."""
    keys = store.list("")
    names: set[str] = set()
    for key in keys:
        m = re.match(
            r"^(?:node_(\d+)/)?(checkpoint_\d+)/" + re.escape(_commit.COMMIT_MARKER) + "$",
            key,
        )
        if m:
            names.add(m.group(2))
    for name in sorted(
        names, key=lambda n: int(n.rsplit("_", 1)[1]), reverse=True
    ):
        procs = set()
        for key in keys:
            m = re.match(
                r"^(?:node_\d+/)?" + re.escape(name) + r"/"
                + re.escape(MODEL_DIR) + r"/index_(\d+)\.json$",
                key,
            )
            if m:
                procs.add(int(m.group(1)))
        if not procs:
            continue
        try:
            src = StoreShardSource(store, name, sorted(procs))
            step_entries = src.shards("step")
            if not step_entries:
                continue
            saved = int(np.asarray(step_entries[0][2]()).reshape(()))
        except Exception as e:
            logger.warning(
                "[atx elastic] remote %s unusable as reshard fallback: %s",
                name,
                e,
            )
            continue
        if saved == int(expected_step):
            return src
        logger.info(
            "[atx elastic] remote %s is at step %d (want %d); not a reshard "
            "fallback",
            name,
            saved,
            expected_step,
        )
    return None


def reshard_arrays(
    template: Any,
    shardings: Any,
    sources: Iterable[Any],
) -> Any:
    """Rebuild ``template``'s jax.Array leaves under new ``shardings`` from
    ``sources`` — the source-agnostic in-memory resharder behind
    shrink/grow-in-place.

    ``template`` supplies structure + global shape/dtype (its leaves may
    live on the OLD mesh); ``shardings`` is a matching pytree of the TARGET
    NamedShardings. ``sources`` are consulted in order per leaf; within a
    leaf their shards are unioned, and `_assemble_slice` only *fetches* a
    later source's shard for regions earlier sources left uncovered.
    Raises `CheckpointShardCoverageError` when the union still has holes
    (callers degrade to the emergency-save + relaunch path)."""
    sources = list(sources)
    flat, treedef = jax.tree_util.tree_flatten_with_path(template)
    shard_leaves = treedef.flatten_up_to(shardings)
    out = []
    for (path, leaf), sharding in zip(flat, shard_leaves):
        key = _leaf_key(path)
        info = None
        for src in sources:
            info = src.leaf_info(key)
            if info is not None:
                break
        if info is None:
            raise KeyError(f"Leaf {key!r} missing from every reshard source")
        if "value" in info:
            out.append(_from_jsonable(info["value"]))
            continue
        shape = tuple(info["shape"])
        dtype = np.dtype(info["dtype"])
        if isinstance(leaf, jax.Array) and tuple(leaf.shape) != shape:
            raise ValueError(
                f"Shape mismatch for {key!r}: template {tuple(leaf.shape)} vs "
                f"source {shape}"
            )
        target_dtype = leaf.dtype if isinstance(leaf, jax.Array) else dtype
        entries = [e for src in sources for e in src.shards(key)]
        arr = jax.make_array_from_callback(
            shape,
            sharding,
            lambda idx, e=entries, s=shape, d=dtype, k=key, td=target_dtype: (
                _assemble_slice(
                    e,
                    idx,
                    s,
                    d,
                    k,
                    context=(
                        "— the surviving processes' live shards plus the "
                        "replicate-store fallback do not cover this leaf; "
                        "shrink-in-place is impossible without data loss"
                    ),
                ).astype(td)
            ),
        )
        out.append(arr)
    return jax.tree_util.tree_unflatten(treedef, out)


def consolidate_checkpoint(directory: str, output_path: str) -> str:
    """Merge a sharded pytree dir into one host file with full arrays —
    the `accelerate merge-weights` analog (reference `utils/fsdp_utils.py:275`).

    The output format follows the extension: ``.safetensors`` writes an
    HF-interchange file (loadable by `transformers`/`safetensors` consumers
    AND by `big_modeling.load_checkpoint_and_dispatch`); anything else
    writes `.npz`. Leaf keys are the pytree paths ("/"-joined), matching
    what the safetensors *reader* here expects back.
    """
    if output_path.endswith(".safetensors"):
        # Import before the (potentially multi-GB) shard read so a missing
        # dependency fails fast, not after minutes of IO.
        from safetensors.numpy import save_file
    reader = _ShardReader(directory)
    merged: dict[str, np.ndarray] = {}
    try:
        for key, info in reader.index.items():
            if "value" in info:
                continue
            merged[key] = reader.read_full(key)
    finally:
        reader.close()
    os.makedirs(os.path.dirname(os.path.abspath(output_path)), exist_ok=True)
    if output_path.endswith(".safetensors"):
        # safetensors requires contiguous buffers.
        save_file({k: np.ascontiguousarray(v) for k, v in merged.items()}, output_path)
        return output_path
    if not output_path.endswith(".npz"):
        output_path = output_path + ".npz"
    np.savez(output_path, **merged)
    return output_path


def _per_proc_pattern(template: str) -> str:
    """Derive a cleanup regex from a ``*_{proc}`` filename template so the
    writer and the stale-file cleaner can never drift apart."""
    return re.escape(template).replace(re.escape("{proc}"), r"\d+")


_SHARD_FILE_PATTERN = re.compile(
    "^(" + "|".join(_per_proc_pattern(t) for t in (INDEX_FILE, SHARDS_FILE)) + ")$"
)


def _clear_stale_files(directory: str, pattern: re.Pattern) -> None:
    if os.path.isdir(directory):
        for name in os.listdir(directory):
            if pattern.match(name):
                try:
                    os.remove(os.path.join(directory, name))
                except FileNotFoundError:
                    # save_on_each_node on a *shared* filesystem (harmless-
                    # redundant config): several processes clear the same dir
                    # concurrently; losing the race is fine.
                    pass


def _clear_stale_shard_files(directory: str, process_state: Any | None = None) -> None:
    """Remove shard/index files left by a previous save into ``directory``.

    Without this, re-saving after the process count shrinks (the advertised
    reshard workflow: save on 2 hosts, later on 1) would leave index_1.json /
    shards_1.npz behind, and the reader — which merges ALL index files — would
    silently mix old weights into the loaded state. Process 0 clears; everyone
    barriers before writing.
    """
    if jax.process_index() == 0:
        _clear_stale_files(directory, _SHARD_FILE_PATTERN)
    if process_state is not None and jax.process_count() > 1:
        process_state.wait_for_everyone()


# ------------------------------------------------------------------- RNG state
def _rng_state_bundle(accelerator: "Accelerator") -> dict[str, Any]:
    return {
        "python_state": _encode_py_random(),
        "numpy_state": _encode_np_random(),
        "jax_key": _encode_jax_key(accelerator.rng),
    }


def _encode_jax_key(key: jax.Array) -> dict[str, Any]:
    import jax.numpy as jnp

    if jnp.issubdtype(key.dtype, jax.dtypes.prng_key):
        return {"typed": True, "data": np.asarray(jax.random.key_data(key)).tolist()}
    return {"typed": False, "data": np.asarray(key).tolist()}


def _decode_jax_key(bundle: dict[str, Any]) -> jax.Array:
    data = np.asarray(bundle["data"], dtype=np.uint32)
    if bundle.get("typed"):
        return jax.random.wrap_key_data(data)
    import jax.numpy as jnp

    return jnp.asarray(data)


def _encode_py_random() -> list[Any]:
    state = _py_random.getstate()
    return json.loads(json.dumps(state, default=list))


def _encode_np_random() -> dict[str, Any]:
    name, keys, pos, has_gauss, cached = np.random.get_state()
    return {
        "name": name,
        "keys": keys.tolist(),
        "pos": int(pos),
        "has_gauss": int(has_gauss),
        "cached": float(cached),
    }


def _restore_rng_bundle(accelerator: "Accelerator", bundle: dict[str, Any]) -> None:
    state = bundle.get("python_state")
    if state:
        version, internal, gauss = state
        _py_random.setstate((version, tuple(internal), gauss))
    np_state = bundle.get("numpy_state")
    if np_state:
        np.random.set_state(
            (
                np_state["name"],
                np.asarray(np_state["keys"], dtype=np.uint32),
                np_state["pos"],
                np_state["has_gauss"],
                np_state["cached"],
            )
        )
    key_bundle = bundle.get("jax_key")
    if key_bundle is not None:
        accelerator.rng = _decode_jax_key(key_bundle)


# ------------------------------------------------------------- rotation naming
def _checkpoint_dirs(root: str) -> list[tuple[int, str]]:
    if not os.path.isdir(root):
        return []
    out = []
    for name in os.listdir(root):
        m = _CKPT_PATTERN.match(name)
        if m:
            out.append((int(m.group(1)), os.path.join(root, name)))
    return sorted(out)


def checkpoint_root(accelerator: "Accelerator") -> str:
    """The automatic-naming checkpoints directory for this project."""
    return os.path.join(accelerator.project_config.project_dir or ".", "checkpoints")


def _resolve_save_dir(accelerator: "Accelerator", output_dir: str | None) -> str:
    """Pick the FINAL directory name for this save. Deliberately does NOT
    delete anything: rotation happens in `_rotate_after_commit`, only after
    the new checkpoint's COMMIT marker lands — deleting first meant a crash
    mid-save with ``total_limit=1`` lost both the old and new checkpoint."""
    cfg = accelerator.project_config
    if cfg.automatic_checkpoint_naming:
        root = checkpoint_root(accelerator)
        existing = _checkpoint_dirs(root)
        iteration = cfg.iteration
        if existing:
            iteration = max(iteration, existing[-1][0] + 1)
        save_dir = os.path.join(root, f"checkpoint_{iteration}")
        cfg.iteration = iteration + 1
        return save_dir
    if output_dir is None:
        raise ValueError("output_dir is required unless automatic_checkpoint_naming is set")
    return output_dir


def _rotate_after_commit(accelerator: "Accelerator", final_dir: str) -> None:
    """Post-commit cleanup (process 0 / the committing process only):
    delete committed checkpoints beyond ``total_limit``, crashed saves'
    ``.tmp`` dirs, and rename-without-marker debris — never the checkpoint
    that just committed, and never before it is durable."""
    cfg = accelerator.project_config
    if not cfg.automatic_checkpoint_naming:
        return
    root = os.path.dirname(final_dir)
    _commit.remove_stale_tmp(root)
    final_abs = os.path.abspath(final_dir)
    committed = _commit.committed_checkpoints(root)
    if cfg.total_limit is not None:
        for _, old in committed[: max(0, len(committed) - cfg.total_limit)]:
            if os.path.abspath(old) != final_abs:
                shutil.rmtree(old, ignore_errors=True)
    # Uncommitted checkpoint_<n> dirs are crash debris (the rename landed,
    # the marker didn't); resume ignores them, so reclaim the disk.
    committed_paths = {os.path.abspath(p) for _, p in _commit.committed_checkpoints(root)}
    for n, path in _checkpoint_dirs(root):
        ap = os.path.abspath(path)
        if ap != final_abs and ap not in committed_paths:
            shutil.rmtree(path, ignore_errors=True)


# --------------------------------------------------------------- async writing
class _AsyncSaver:
    """Serializes background checkpoint writes; one in flight at a time."""

    def __init__(self) -> None:
        self._thread: threading.Thread | None = None
        self._error: list[BaseException] = []

    def wait(self) -> None:
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        if self._error:
            err = self._error[0]
            self._error.clear()
            raise err

    def submit(self, fn, *args: Any) -> None:
        self.wait()

        def run() -> None:
            try:
                fn(*args)
            except BaseException as e:  # re-raised on next wait()
                # Log NOW: the next wait() may be many steps away (or never
                # come), and a background save that silently failed is
                # exactly the data loss this layer exists to prevent.
                logger.exception("async checkpoint save failed: %s", e)
                self._error.append(e)

        self._thread = threading.Thread(target=run, daemon=False)
        self._thread.start()


_ASYNC_SAVER = _AsyncSaver()


def wait_for_checkpoint() -> None:
    """Block until any in-flight async save completes (and re-raise errors)."""
    _ASYNC_SAVER.wait()


def _wait_for_checkpoint_at_exit() -> None:
    # A clean interpreter exit must never truncate an in-flight async save:
    # join it (and surface its error as a log, not a raise — atexit is no
    # place for a traceback fight).
    try:
        _ASYNC_SAVER.wait()
    except BaseException:
        logger.exception("async checkpoint save failed during interpreter exit")


atexit.register(_wait_for_checkpoint_at_exit)


@contextlib.contextmanager
def _watchdog_paused():
    """Suspend the hang watchdog (ATX_WATCHDOG_SECS) across the blocking
    save/load work. A routine synchronous checkpoint between steps
    legitimately exceeds a per-step deadline; without this the watchdog
    would dump stacks and abort mid-commit — a false positive that loses
    the in-flight checkpoint and burns a --max_restarts attempt. The
    countdown restarts on exit iff it was armed (heartbeat semantics)."""
    from .resilience.watchdog import watchdog_from_env

    wd = watchdog_from_env()
    if wd is None:
        yield
        return
    with wd.paused():
        yield


# ---------------------------------------------------------------- entry points
def save_state(
    accelerator: "Accelerator",
    output_dir: str | None,
    state: "TrainState",
    *,
    dataloaders: Iterable[Any] | None = None,
    async_save: bool = False,
) -> str:
    """Full training-state checkpoint (reference `save_state`,
    `accelerator.py:3106`): TrainState pytree (sharded), RNG bundle, step,
    dataloader iterator states, registered custom objects.

    Crash-safe: every file goes into ``<dir>.tmp/``, each process writes a
    SHA-256 manifest over its files, and only after a multi-host barrier
    does process 0 rename to the final name and write the ``COMMIT`` marker
    (`resilience/commit.py`). Rotation deletes old checkpoints strictly
    AFTER the new commit lands. The async path runs the same
    write → manifest → commit sequence from the background thread.

    The hang watchdog is paused for the duration (`_watchdog_paused`): a
    between-steps save is legitimate long host work, not a wedged step.
    """
    with _watchdog_paused():
        return _save_state_impl(
            accelerator,
            output_dir,
            state,
            dataloaders=dataloaders,
            async_save=async_save,
        )


def _save_state_impl(
    accelerator: "Accelerator",
    output_dir: str | None,
    state: "TrainState",
    *,
    dataloaders: Iterable[Any] | None = None,
    async_save: bool = False,
) -> str:
    # Join any in-flight async save first: a new save (or its rotation) must
    # never touch a directory a background writer is still filling. The
    # local join is not enough on multi-host — barrier after every host has
    # joined its own writer.
    wait_for_checkpoint()
    if jax.process_count() > 1:
        accelerator.process_state.wait_for_everyone()
    proc = jax.process_index()
    each_node = accelerator.project_config.save_on_each_node
    if proc == 0 or each_node:
        # save_on_each_node: every process has its own filesystem, so each
        # resolves (and later writes) locally; with automatic naming the
        # broadcast below still forces process 0's choice everywhere.
        final_dir = _resolve_save_dir(accelerator, output_dir)
    else:
        final_dir = None
    if jax.process_count() > 1:
        # All hosts must agree on the directory (independent filesystem
        # listings race under automatic_checkpoint_naming).
        from .ops.collectives import broadcast_object_list

        final_dir = broadcast_object_list([final_dir])[0]
    tmp_dir = final_dir + _commit.TMP_SUFFIX
    if proc == 0 or each_node:
        # A previous save into this name may have crashed mid-write; the tmp
        # dir is ours now. Writing into a FRESH tmp dir also retires the old
        # shrink-hosts staleness problem (stale index_1/shards_1/rng_state_1
        # from a larger process count can't exist in a new directory).
        shutil.rmtree(tmp_dir, ignore_errors=True)
    if jax.process_count() > 1:
        accelerator.process_state.wait_for_everyone()
    os.makedirs(os.path.join(tmp_dir, MODEL_DIR), exist_ok=True)

    saveable = {"step": state.step, "params": state.params, "opt_state": state.opt_state}
    if state.loss_scale is not None:
        saveable["loss_scale"] = state.loss_scale
    step_value = int(jax.device_get(state.step))

    # Small host-side files first (both paths): the manifest must cover
    # every file this process writes, and on the async path it is written
    # by the background thread after the (slow) shard write finishes.
    written: list[str] = []
    with open(os.path.join(tmp_dir, RNG_FILE.format(proc=proc)), "w") as f:
        json.dump(_rng_state_bundle(accelerator), f)
    written.append(RNG_FILE.format(proc=proc))

    # On a shared filesystem only process 0 writes the process-agnostic
    # artifacts (metadata, dataloader states, custom objects); with
    # save_on_each_node every process writes them so each node's local
    # directory is self-contained (reference `ProjectConfiguration.
    # save_on_each_node`, consumed at `accelerator.py:2979,3129`).
    if proc == 0 or each_node:
        dls = list(dataloaders) if dataloaders is not None else accelerator._dataloaders
        dl_states = [dl.state_dict() for dl in dls]
        with open(os.path.join(tmp_dir, DATALOADER_FILE), "w") as f:
            json.dump(dl_states, f)
        written.append(DATALOADER_FILE)
        for i, obj in enumerate(accelerator._checkpoint_registry):
            with open(os.path.join(tmp_dir, CUSTOM_FILE.format(i=i)), "wb") as f:
                pickle.dump(obj.state_dict(), f)
            written.append(CUSTOM_FILE.format(i=i))
        with open(os.path.join(tmp_dir, METADATA_FILE), "w") as f:
            # v2 records the full topology signature (mesh axis sizes,
            # process count, device count) so a restore can detect that the
            # pod came back at a different size and engage the elastic
            # reshard path. v1 readers ignore the extra key; v1 checkpoints
            # (no num_devices) compare permissively on the recorded fields.
            json.dump(
                {
                    "step": step_value,
                    **_mesh.topology_signature(accelerator.mesh),
                    "version": 2,
                },
                f,
            )
        written.append(METADATA_FILE)

    def _write_shards_and_manifest(model_tree: Any) -> None:
        save_pytree(model_tree, os.path.join(tmp_dir, MODEL_DIR), process_index=proc)
        _fault_point("save.files_written")
        files = written + [
            os.path.join(MODEL_DIR, SHARDS_FILE.format(proc=proc)),
            os.path.join(MODEL_DIR, INDEX_FILE.format(proc=proc)),
        ]
        # The manifest records this process's step: verify_checkpoint
        # rejects a checkpoint whose shards mix steps (processes entering
        # save_state one step apart would otherwise commit garbage).
        _commit.write_manifest(tmp_dir, proc, files, step=step_value)
        _fault_point("save.manifest_written")

    if async_save:
        # Synchronously snapshot device data to host, write files off-thread
        # through the same writer as the sync path (one on-disk format); the
        # background job finishes with manifest + commit so a checkpoint is
        # never discoverable before it is whole.
        host_tree = jax.tree.map(
            lambda x: _HostShardSnapshot(x) if isinstance(x, jax.Array) else x, saveable
        )

        def _async_job() -> None:
            _write_shards_and_manifest(host_tree)
            _barrier_and_commit(
                accelerator, tmp_dir, final_dir, step_value, file_barrier=True
            )

        _ASYNC_SAVER.submit(_async_job)
    else:
        _write_shards_and_manifest(saveable)
        _barrier_and_commit(
            accelerator, tmp_dir, final_dir, step_value, file_barrier=False
        )
    return final_dir


def _barrier_and_commit(
    accelerator: "Accelerator",
    tmp_dir: str,
    final_dir: str,
    step_value: int,
    *,
    file_barrier: bool,
) -> None:
    """Every process's files are on disk → barrier → the committing process
    renames tmp → final, writes COMMIT last, then rotates.

    The sync path barriers with the real collective; the async path runs on
    a background thread, which must not issue collectives the main thread
    may also be using, so it barriers through ``.precommit_<proc>`` marker
    files on the shared filesystem instead. With ``save_on_each_node`` each
    process owns (and commits) its node-local directory.
    """
    proc = jax.process_index()
    nproc = jax.process_count()
    # The marker carries the topology signature too: it is the first file a
    # restore reads, and save_on_each_node directories have no metadata.json
    # from every process — the signature must survive in the per-node copy.
    meta = {"step": step_value, **_mesh.topology_signature(accelerator.mesh)}
    if accelerator.project_config.save_on_each_node:
        # Each node commits its own local directory carrying ONE manifest;
        # flag it so verify_checkpoint's completeness check (manifest count
        # vs num_processes) knows not to demand all of them here.
        _commit.write_aggregate_manifest(tmp_dir)
        _commit.commit_dir(tmp_dir, final_dir, {**meta, "save_on_each_node": True})
        _rotate_after_commit(accelerator, final_dir)
        _notify_replicator(accelerator, final_dir, proc, nproc, each_node=True)
        return
    if nproc > 1:
        if file_barrier:
            _commit.mark_precommit(tmp_dir, proc)
            if proc == 0:
                _commit.wait_for_precommit(
                    tmp_dir,
                    nproc,
                    timeout_secs=get_int_from_env(("ATX_COMMIT_BARRIER_SECS",), 600),
                )
        else:
            accelerator.process_state.wait_for_everyone()
    if proc == 0:
        # Every peer's manifest is visible after the barrier: collapse them
        # into MANIFEST.agg.json so the committed directory is verifiable
        # even where peers' manifest files aren't (per-node filesystems,
        # object-store replicas) — pure file IO, no collective.
        _commit.write_aggregate_manifest(tmp_dir)
        _commit.commit_dir(tmp_dir, final_dir, meta)
        _rotate_after_commit(accelerator, final_dir)
        _notify_replicator(accelerator, final_dir, proc, nproc, each_node=False)
    if nproc > 1 and not file_barrier:
        # Sync saves return only once the committed dir is visible to every
        # rank (callers immediately load/inspect the returned path).
        accelerator.process_state.wait_for_everyone()


def _notify_replicator(
    accelerator: "Accelerator",
    final_dir: str,
    proc: int,
    nproc: int,
    *,
    each_node: bool,
) -> None:
    """Hand the freshly committed checkpoint to the background Replicator
    (when ``ATX_REPLICATE_URL`` configured one). Runs only on the committing
    process, does no IO itself (one queue put), and therefore adds nothing
    to the collective schedule. Only automatic-naming checkpoints replicate:
    the remote layout (and remote rotation) keys on ``checkpoint_<n>``."""
    replicator = getattr(accelerator, "_replicator", None)
    if replicator is None or not accelerator.project_config.automatic_checkpoint_naming:
        return
    replicator.enqueue(
        final_dir,
        process_index=proc,
        num_processes=nproc,
        each_node=each_node,
        total_limit=accelerator.project_config.total_limit,
    )


def _backfill_replicator(accelerator: "Accelerator", final_dir: str) -> None:
    """A checkpoint that committed locally right before a crash may never
    have finished uploading (a kill -9 mid-upload leaves parts but no remote
    ``COMMIT``). On resume, re-enqueue the checkpoint being resumed from:
    the Replicator skips parts — and whole checkpoints — already durable
    remotely, so this converges to one full remote commit instead of
    leaving the newest checkpoint local-only forever."""
    proc = jax.process_index()
    nproc = jax.process_count()
    each_node = bool(accelerator.project_config.save_on_each_node)
    if proc != 0 and not each_node:
        return
    _notify_replicator(accelerator, final_dir, proc, nproc, each_node=each_node)


_REMOTE_RESTORE_SENTINEL = ".remote_restore_done"


def _remote_restore(accelerator: "Accelerator", root: str) -> str | None:
    """``resume="latest"`` fallback: when the local checkpoints root holds
    nothing usable, download the newest remote *committed* checkpoint
    (``ATX_REPLICATE_URL``) into ``root``. Returns the committed, verified
    local path or None (no store configured / nothing durable remotely).

    No collectives: on a shared filesystem process 0 downloads and then
    records its verdict in a ``.remote_restore_done`` sentinel; peers poll
    for the sentinel (and re-verify the directory it names) instead of
    barriering — resume happens at startup, where a fresh collective would
    change the schedule the ATX5xx lint pins. ``save_on_each_node`` roots
    are per-process, so every process restores its own node directory.
    """
    # Prefer the store the Accelerator armed at construction time (the env
    # may have changed since); fall back to the env for restore-only setups
    # where replication uploads were never enabled.
    replicator = getattr(accelerator, "_replicator", None)
    store = replicator.store if replicator is not None else _replicate.store_from_env()
    if store is None:
        return None
    proc = jax.process_index()
    nproc = jax.process_count()
    each_node = bool(accelerator.project_config.save_on_each_node)
    if each_node or nproc == 1:
        return _replicate.restore_latest(
            store,
            root,
            process_index=proc,
            num_processes=nproc,
            each_node=each_node,
        )
    sentinel = os.path.join(root, _REMOTE_RESTORE_SENTINEL)
    if proc == 0:
        os.makedirs(root, exist_ok=True)
        try:
            os.remove(sentinel)
        except FileNotFoundError:
            pass
        restored = None
        try:
            restored = _replicate.restore_latest(store, root)
        finally:
            with open(sentinel, "w") as f:
                f.write(os.path.basename(restored) if restored else "")
                f.flush()
                os.fsync(f.fileno())
        return restored
    deadline = time.monotonic() + _replicate._env_float(
        "ATX_REPLICATE_TIMEOUT_SECS", 600.0
    )
    while time.monotonic() < deadline:
        if os.path.exists(sentinel):
            with open(sentinel) as f:
                name = f.read().strip()
            if not name:
                return None  # process 0 found nothing usable remotely
            candidate = os.path.join(root, name)
            if _commit.is_committed(candidate) and not _commit.verify_checkpoint(
                candidate
            ):
                return candidate
            return None
        time.sleep(0.25)
    logger.warning(
        "timed out waiting for process 0's remote checkpoint restore under %s",
        root,
    )
    return None


class _HostShardSnapshot:
    """Host-side copy of a jax.Array's replica-0 shards (taken synchronously
    so training can mutate/donate the device buffers while files write)."""

    def __init__(self, arr: jax.Array, *, process_index: int | None = None) -> None:
        proc = jax.process_index() if process_index is None else process_index
        self.shape = tuple(arr.shape)
        self.dtype = np.dtype(arr.dtype)
        self.ndim = arr.ndim
        self.spec = _serialize_spec(getattr(arr, "sharding", None))
        self.shards: list[tuple[tuple[int, ...], np.ndarray]] = []
        any_replica0 = False
        for shard in arr.addressable_shards:
            if shard.replica_id != 0:
                continue
            any_replica0 = True
            starts = tuple((sl.start or 0) for sl in shard.index) if arr.ndim else ()
            self.shards.append((starts, np.asarray(shard.data)))
        if not any_replica0 and arr.is_fully_replicated and proc == 0:
            # replica_id bookkeeping can mark all local shards non-zero on
            # some topologies; main process persists replicated leaves.
            self.shards.append(((0,) * arr.ndim, np.asarray(arr)))



def saved_topology(input_dir: str) -> dict | None:
    """The topology signature a checkpoint was saved under — from the
    ``COMMIT`` marker first (present in every committed directory, including
    per-node copies), ``metadata.json`` as fallback. None for legacy
    pre-metadata checkpoints (which then load permissively, exactly as
    before this metadata existed)."""
    sources: list[dict[str, Any]] = []
    if _commit.is_committed(input_dir):
        try:
            sources.append(_commit.read_commit_marker(input_dir))
        except (ValueError, OSError):
            pass
    meta_path = os.path.join(input_dir, METADATA_FILE)
    if os.path.exists(meta_path):
        try:
            with open(meta_path) as f:
                sources.append(json.load(f))
        except (ValueError, OSError):
            pass
    for src in sources:
        sig = {
            k: src[k]
            for k in ("mesh", "num_processes", "num_devices")
            if src.get(k) is not None
        }
        if sig:
            return sig
    return None


def _ensure_shard_coverage(
    accelerator: "Accelerator", input_dir: str, saved: dict | None
) -> dict[int, tuple[Any, str, dict]]:
    """Elastic-restore prelude: make every saved process's shard files
    reachable before `load_pytree` assembles globals.

    On a shared filesystem all ``index_<p>.json``/``shards_<p>.npz`` files
    are already local and this returns ``{}``. With ``save_on_each_node``
    (or a partially-lost root) the peers' files live under the replicate
    store — ``node_<p>/<name>/`` prefixes, or the flat ``<name>/`` prefix
    the shared-fs Replicator uploads everything under. For those procs the
    (small) JSON index is read into memory and verified against the peer's
    remote manifest; the returned refs make `_ShardReader` fetch individual
    shard members by byte range (``ObjectStore.get_range``, same machinery
    as the live-shrink `StoreShardSource`) instead of streaming whole
    archives — a reshard that needs a few rows of a peer's npz no longer
    downloads all of it, and nothing is ever written into the committed
    directory. ``ATX_RESTORE_RANGED=0`` restores the legacy behaviour
    (atomic whole-file download + rename into the checkpoint dir). Anything
    still missing or corrupt surfaces later as
    `CheckpointShardCoverageError` (never a silent partial reshard).
    """
    model_dir = os.path.join(input_dir, MODEL_DIR)
    want = int((saved or {}).get("num_processes") or 0)
    if want <= 1:
        return {}
    have: set[int] = set()
    if os.path.isdir(model_dir):
        for name in os.listdir(model_dir):
            m = re.match(r"^index_(\d+)\.json$", name)
            # A proc counts as covered only with BOTH files: a fetch killed
            # between index and shards must be retried, not trusted.
            if m and os.path.exists(
                os.path.join(model_dir, SHARDS_FILE.format(proc=int(m.group(1))))
            ):
                have.add(int(m.group(1)))
    missing = [p for p in range(want) if p not in have]
    if not missing:
        return {}
    replicator = getattr(accelerator, "_replicator", None)
    store = replicator.store if replicator is not None else _replicate.store_from_env()
    if store is None:
        logger.warning(
            "elastic restore of %s: %d saved process(es) have no shard files "
            "here and no replicate store is configured (ATX_REPLICATE_URL) — "
            "the restore fails with CheckpointShardCoverageError if any leaf "
            "needs them",
            input_dir,
            len(missing),
        )
        return {}
    name = os.path.basename(os.path.abspath(input_dir))
    ranged = os.environ.get("ATX_RESTORE_RANGED", "1").strip().lower() not in (
        "0",
        "off",
        "false",
        "no",
    )
    if not ranged:
        _fetch_peer_shards_whole(input_dir, store, name, missing)
        return {}
    refs: dict[int, tuple[Any, str, dict]] = {}
    for p in missing:
        ref = _remote_shard_ref(store, name, p, input_dir)
        if ref is not None:
            refs[p] = ref
            logger.info(
                "elastic restore of %s: process %d's shards will be read by "
                "byte range from %r",
                input_dir,
                p,
                store,
            )
        else:
            logger.warning(
                "elastic restore of %s: process %d's shard files are not in "
                "%r either — the restore fails with "
                "CheckpointShardCoverageError if any leaf needs them",
                input_dir,
                p,
                store,
            )
    return refs


def _remote_shard_ref(
    store: Any, name: str, proc: int, input_dir: str
) -> tuple[Any, str, dict] | None:
    """Locate process ``proc``'s checkpoint under the store and return a
    ``(store, npz_key, index)`` ranged-read ref, or ``None`` when neither
    prefix has it. Only the JSON index is transferred (and sha-verified
    against the peer's remote manifest when one exists); shard bytes stay
    remote until a leaf actually needs them."""
    idx_rel = f"{MODEL_DIR}/{INDEX_FILE.format(proc=proc)}"
    npz_rel = f"{MODEL_DIR}/{SHARDS_FILE.format(proc=proc)}"
    for prefix in (f"node_{proc}/{name}", name):
        try:
            if not store.exists(f"{prefix}/{idx_rel}"):
                continue
            raw = store.get_bytes(f"{prefix}/{idx_rel}")
            _verify_remote_bytes(store, prefix, proc, idx_rel, raw)
            index = json.loads(raw.decode())
        except Exception as e:
            logger.warning(
                "elastic restore of %s: reading process %d's index from "
                "%r/%s failed: %s",
                input_dir,
                proc,
                store,
                prefix,
                e,
            )
            continue
        _fault_point("restore.peer_shard_fetched")
        return store, f"{prefix}/{npz_rel}", index
    return None


def _verify_remote_bytes(
    store: Any, prefix: str, proc: int, rel: str, raw: bytes
) -> None:
    """Best-effort hash check of in-memory remote bytes against the peer's
    remote manifest — the ranged-path twin of `_verify_fetched_shards`. A
    store with no manifest passes (read_slice coverage is the backstop)."""
    try:
        manifest = json.loads(
            store.get_bytes(
                f"{prefix}/{_commit.MANIFEST_FILE.format(proc=proc)}"
            ).decode()
        )
    except Exception:
        return
    info = manifest.get("files", {}).get(rel)
    if info is not None and hashlib.sha256(raw).hexdigest() != info["sha256"]:
        raise ValueError(
            f"fetched {rel} does not match process {proc}'s remote manifest"
        )


def _fetch_peer_shards_whole(
    input_dir: str, store: Any, name: str, missing: list[int]
) -> None:
    """Legacy (``ATX_RESTORE_RANGED=0``) coverage: download each missing
    process's index+shards pair whole into the checkpoint directory.
    Fetches are atomic (``.fetch`` tmp + rename) and verified against the
    peer's remote manifest when one exists."""
    for p in missing:
        rels = [
            f"{MODEL_DIR}/{INDEX_FILE.format(proc=p)}",
            f"{MODEL_DIR}/{SHARDS_FILE.format(proc=p)}",
        ]
        fetched = False
        for prefix in (f"node_{p}/{name}", name):
            if not store.exists(f"{prefix}/{rels[0]}"):
                continue
            # Download + verify into ``.fetch`` siblings first; the committed
            # directory only changes in the final all-or-nothing rename pass,
            # so a crash mid-fetch leaves the checkpoint exactly as it was.
            pending: list[tuple[str, str, str]] = []
            try:
                for rel in rels:
                    dst = os.path.join(input_dir, rel.replace("/", os.sep))
                    tmp = dst + ".fetch"
                    store.get_file(f"{prefix}/{rel}", tmp)
                    pending.append((rel, tmp, dst))
                    _fault_point("restore.peer_shard_fetched")
                _verify_fetched_shards(store, prefix, p, pending)
                for _, tmp, dst in pending:
                    os.replace(tmp, dst)
                fetched = True
                break
            except Exception as e:
                for _, tmp, _ in pending:
                    try:
                        os.remove(tmp)
                    except OSError:
                        pass
                logger.warning(
                    "elastic restore of %s: fetching process %d's shard "
                    "files from %r/%s failed: %s",
                    input_dir,
                    p,
                    store,
                    prefix,
                    e,
                )
        if fetched:
            logger.info(
                "elastic restore of %s: fetched process %d's shard files "
                "from %r",
                input_dir,
                p,
                store,
            )
        else:
            logger.warning(
                "elastic restore of %s: process %d's shard files are not in "
                "%r either — the restore fails with "
                "CheckpointShardCoverageError if any leaf needs them",
                input_dir,
                p,
                store,
            )


def _verify_fetched_shards(
    store: Any, prefix: str, proc: int, pending: list[tuple[str, str, str]]
) -> None:
    """Best-effort hash check of just-downloaded peer shard files (still at
    their ``.fetch`` tmp paths) against the peer's remote manifest. A
    mismatch raises BEFORE anything is renamed into the committed directory;
    a store with no manifest passes — `read_slice` coverage is the backstop."""
    try:
        manifest = json.loads(
            store.get_bytes(
                f"{prefix}/{_commit.MANIFEST_FILE.format(proc=proc)}"
            ).decode()
        )
    except Exception:
        return
    for rel, tmp, _ in pending:
        info = manifest.get("files", {}).get(rel)
        if info is None or not os.path.exists(tmp):
            continue
        if _commit.file_sha256(tmp) != info["sha256"]:
            raise ValueError(
                f"fetched {rel} does not match process {proc}'s remote manifest"
            )


def load_state(
    accelerator: "Accelerator",
    input_dir: str | None,
    state: "TrainState",
    *,
    dataloaders: Iterable[Any] | None = None,
    resume: str | None = None,
) -> "TrainState":
    """Restore a `save_state` checkpoint into ``state``'s shardings
    (reference `load_state`, `accelerator.py:3272`).

    ``resume="latest"`` treats ``input_dir`` as a checkpoints ROOT (default:
    ``<project_dir>/checkpoints``, the automatic-naming layout) and restores
    the newest *committed* checkpoint whose SHA-256 manifest verifies —
    skipping uncommitted crash debris entirely and, when the newest
    committed checkpoint is corrupt (truncated/bit-flipped/partially
    deleted), warning and falling back to the previous committed one
    instead of crashing or training on garbage.

    An explicit ``input_dir`` (no ``resume``) is verified too when it
    carries a manifest; corruption raises (the caller named THIS
    checkpoint, silently substituting another would be worse). Pre-manifest
    legacy checkpoints load as before.

    Like `save_state`, the hang watchdog is paused for the duration — a
    restore (verification hashes every shard) is legitimate long host work.
    """
    with _watchdog_paused():
        return _load_state_impl(
            accelerator, input_dir, state, dataloaders=dataloaders, resume=resume
        )


def _load_state_impl(
    accelerator: "Accelerator",
    input_dir: str | None,
    state: "TrainState",
    *,
    dataloaders: Iterable[Any] | None = None,
    resume: str | None = None,
) -> "TrainState":
    wait_for_checkpoint()
    if resume is not None:
        if resume != "latest":
            raise ValueError(f"resume={resume!r}: the only supported policy is 'latest'")
        root = input_dir if input_dir is not None else checkpoint_root(accelerator)
        candidates = _commit.committed_checkpoints(root)
        if not candidates:
            # Empty/lost local root (preempted VM, fresh node): fall back to
            # the newest remote committed checkpoint when replication is on.
            restored = _remote_restore(accelerator, root)
            if restored is not None:
                logger.info(
                    "local root %s has no committed checkpoint; resuming "
                    "from remote-restored %s",
                    root,
                    restored,
                )
                return _load_state_dir(
                    accelerator, restored, state, dataloaders=dataloaders
                )
            raise FileNotFoundError(
                f"no committed checkpoint under {root!r} (directories without "
                f"a {_commit.COMMIT_MARKER} marker are incomplete saves and "
                "are never resumed from)"
            )
        failures: list[str] = []
        for _, candidate in reversed(candidates):
            errors = _commit.verify_checkpoint(candidate)
            if errors:
                warnings.warn(
                    f"committed checkpoint {candidate} failed integrity "
                    f"verification ({'; '.join(errors[:3])}); falling back to "
                    "the previous committed checkpoint",
                    CheckpointIntegrityWarning,
                    stacklevel=2,
                )
                failures.append(f"{candidate}: {'; '.join(errors[:3])}")
                continue
            logger.info("resuming from committed checkpoint %s", candidate)
            _backfill_replicator(accelerator, candidate)
            try:
                return _load_state_dir(
                    accelerator, candidate, state, dataloaders=dataloaders
                )
            except CheckpointShardCoverageError as e:
                # A partial reshard would silently resume on garbage;
                # fall back to the previous committed checkpoint instead.
                warnings.warn(
                    f"committed checkpoint {candidate} cannot be fully "
                    f"assembled at the current topology ({e}); falling back "
                    "to the previous committed checkpoint",
                    CheckpointIntegrityWarning,
                    stacklevel=2,
                )
                failures.append(f"{candidate}: {e}")
                continue
        # Every local checkpoint is corrupt: a remote replica may still be
        # intact (restore_latest re-downloads over corrupt local copies).
        restored = _remote_restore(accelerator, root)
        if restored is not None:
            warnings.warn(
                f"every committed checkpoint under {root!r} failed integrity "
                f"verification; resuming from remote-restored {restored}",
                CheckpointIntegrityWarning,
                stacklevel=2,
            )
            return _load_state_dir(
                accelerator, restored, state, dataloaders=dataloaders
            )
        raise ValueError(
            f"every committed checkpoint under {root!r} failed integrity "
            f"verification: {failures}"
        )
    if input_dir is None:
        raise ValueError("input_dir is required unless resume='latest' is passed")
    errors = _commit.verify_checkpoint(input_dir)
    if errors:
        raise ValueError(
            f"checkpoint at {input_dir!r} failed integrity verification: "
            f"{'; '.join(errors)} — restore from another checkpoint (or use "
            "load_state(..., resume='latest') on the checkpoints root to "
            "fall back automatically)"
        )
    try:
        return _load_state_dir(accelerator, input_dir, state, dataloaders=dataloaders)
    except CheckpointShardCoverageError as e:
        saved = saved_topology(input_dir)
        raise CheckpointShardCoverageError(
            f"checkpoint at {input_dir!r} cannot be fully assembled at the "
            "current topology "
            f"({_mesh.describe_topology(_mesh.topology_signature(accelerator.mesh))}); "
            f"it was saved under {_mesh.describe_topology(saved)}. {e} — "
            "fixes: arm ATX_REPLICATE_URL so missing peer shard files are "
            "fetched from the replicate store, restore at the saved "
            "topology, or use resume='latest' on the checkpoints root to "
            "fall back to an older checkpoint automatically"
        ) from e


def _load_state_dir(
    accelerator: "Accelerator",
    input_dir: str,
    state: "TrainState",
    *,
    dataloaders: Iterable[Any] | None = None,
) -> "TrainState":
    saved = saved_topology(input_dir)
    remote_shards: dict | None = None
    if not _mesh.topology_matches(saved, accelerator.mesh):
        # Elastic reshard-on-restore: the pod came back at a different
        # size/slice. The on-disk format is already topology-independent
        # (global shape + shard table per leaf; load_pytree reassembles any
        # slice) — what changes here is reach: peers' shard files may live
        # on nodes that no longer exist, so pull them from the replicate
        # store first, and say loudly what is happening.
        logger.warning(
            "checkpoint %s was saved under %s; restoring onto %s — elastic "
            "reshard-on-restore engaged (every leaf is reassembled from the "
            "saved shard files and re-laid onto the current mesh)",
            input_dir,
            _mesh.describe_topology(saved),
            _mesh.describe_topology(_mesh.topology_signature(accelerator.mesh)),
        )
        remote_shards = _ensure_shard_coverage(accelerator, input_dir, saved)
    model_dir = os.path.join(input_dir, MODEL_DIR)
    target = {"step": state.step, "params": state.params, "opt_state": state.opt_state}
    if state.loss_scale is not None and _index_has_prefix(model_dir, "loss_scale"):
        # Only restore the scaler when the checkpoint has one: an fp16 resume
        # from a pre-scaler (or bf16-trained) checkpoint keeps the fresh scaler.
        target["loss_scale"] = state.loss_scale
    restored = load_pytree(target, model_dir, remote_shards=remote_shards)

    rng_path = os.path.join(input_dir, RNG_FILE.format(proc=jax.process_index()))
    if not os.path.exists(rng_path):
        rng_path = os.path.join(input_dir, RNG_FILE.format(proc=0))
    if os.path.exists(rng_path):
        with open(rng_path) as f:
            _restore_rng_bundle(accelerator, json.load(f))

    dl_path = os.path.join(input_dir, DATALOADER_FILE)
    if os.path.exists(dl_path):
        with open(dl_path) as f:
            dl_states = json.load(f)
        dls = list(dataloaders) if dataloaders is not None else accelerator._dataloaders
        for dl, dl_state in zip(dls, dl_states):
            dl.load_state_dict(dl_state)

    for i, obj in enumerate(accelerator._checkpoint_registry):
        path = os.path.join(input_dir, CUSTOM_FILE.format(i=i))
        if os.path.exists(path):
            with open(path, "rb") as f:
                obj.load_state_dict(pickle.load(f))

    return state.replace(
        step=restored["step"],
        params=restored["params"],
        opt_state=restored["opt_state"],
        loss_scale=restored.get("loss_scale", state.loss_scale),
    )


def save_model(
    accelerator: "Accelerator",
    params: Any,
    output_dir: str,
    *,
    consolidate: bool = True,
) -> str:
    """Inference checkpoint of params only (reference `save_model`,
    `accelerator.py:2963`). Sharded layout, optionally merged to one file."""
    model_dir = os.path.join(output_dir, "model")
    _clear_stale_shard_files(model_dir, accelerator.process_state)
    save_pytree(params, model_dir)
    # Every host must finish writing its shard files before the merge reads.
    accelerator.process_state.wait_for_everyone()
    if not consolidate:
        return model_dir
    merged = os.path.join(output_dir, "model.npz")
    if jax.process_index() == 0:
        consolidate_checkpoint(model_dir, merged)
    # All ranks return the same (fully written) merged path: barrier after
    # the merge so rank>0 never sees a missing/partial model.npz.
    accelerator.process_state.wait_for_everyone()
    return merged

"""The `Accelerator` facade — the framework's single user-facing entry point.

TPU-native redesign of the reference `Accelerator` (`accelerator.py:175`,
3,769 LoC). The reference rewrites torch objects so an eager loop becomes
distributed; here "prepare" means **build mesh + shardings + one jitted train
step over sharded pytrees** (SURVEY.md §7 design stance). The reference's
training-loop choreography —

    with accelerator.accumulate(model):
        out = model(batch); accelerator.backward(loss)
        accelerator.clip_grad_norm_(...); optimizer.step(); scheduler.step()

— collapses into `state, metrics = train_step(state, batch)` where the step
internally: scans over microbatches (grad accumulation, `accelerator.py:1116`
`accumulate`), casts to the compute dtype (autocast, :1462-1473), clips by
global norm (`clip_grad_norm_` :2485), applies the optax update (optimizer
step + LR schedule), and lets GSPMD insert the gradient reductions that DDP's
C++ reducer performed (:1519-1544).

Capability parity index (reference `accelerator.py` line refs):
- prepare                      :1283  -> `prepare` / `prepare_data_loader` /
                                         `create_train_state` / `make_train_step`
- accumulate/no_sync           :1116  -> `gradient_accumulation_steps` (scan)
- backward                     :2357  -> inside the jitted step
- clip_grad_norm_              :2485  -> `max_grad_norm` / clipping in-step
- clip_grad_value_             :2523  -> `max_grad_value` elementwise clamp in-step
- gather/gather_for_metrics    :2569/:2601 -> `gather` / `gather_for_metrics`
- reduce/pad_across_processes  :2704/:2679 -> re-exported ops
- unwrap_model                 :2745  -> `unwrap` (identity on pytrees)
- save/load_state              :3106/:3272 -> checkpointing milestone
- autocast                     :3587  -> `MixedPrecisionPolicy`
- free_memory                  :3412  -> `free_memory`
- trigger flags                :2391  -> `set_trigger`/`check_trigger`
- join_uneven_inputs           :1161  -> not needed: even_batches wraparound
                                         keeps SPMD steps uniform by design
"""

from __future__ import annotations

import gc
import os
from typing import Any, Callable, Iterable, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import struct
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from .data.loader import DataLoader
from .ops import collectives as _ops
from .ops import fp8 as _fp8
from .parallel.mesh import (
    BATCH_AXES,
    TENSOR_AXIS,
    MeshConfig,
    batch_sharding,
    build_mesh,
    data_parallel_size,
    resize_mesh_config,
    topology_signature,
    use_mesh,
)
from .parallel.sharding import (
    ShardingStrategy,
    infer_opt_specs,
    infer_param_specs,
    shard_pytree,
    to_named_shardings,
)
from .state import AcceleratorState, GradientState, ProcessState
from .utils.dataclasses import (
    DataLoaderConfiguration,
    GradientAccumulationPlugin,
    MixedPrecisionPolicy,
    ProjectConfiguration,
)
from .utils.random import set_seed as _set_seed


def _warn_fp8_noop() -> None:
    """mixed_precision='fp8' only has an effect for models whose projections
    route through `matmul_einsum` (the in-repo model zoo does; arbitrary user
    models may not). Runs at trace time, so it fires once per compilation."""
    import warnings

    warnings.warn(
        "mixed_precision='fp8' had no effect: the traced loss_fn never routed "
        "a matmul through accelerate_tpu.models.layers.matmul_einsum, so the "
        "whole step ran in bf16. Use the in-repo model layers (or call "
        "matmul_einsum for your projections) to get real fp8 matmuls.",
        stacklevel=2,
    )


class NonFiniteGuardError(RuntimeError):
    """``ATX_NAN_GUARD`` ran out of patience: the training step produced a
    non-finite loss or gradients for ``ATX_NAN_GUARD_MAX_CONSECUTIVE``
    consecutive steps. Each bad step's optimizer update was *skipped* inside
    the compiled step (params/opt-state untouched), so the state this error
    leaves behind is the last finite one — checkpoint it, lower the LR /
    inspect the data, and resume. A budget-exceeded streak almost always
    means divergence, not a transient batch."""


_UNPINNED_WARNED: set[str] = set()


def _warn_unpinned_once(message: str) -> None:
    """Trace-time warning for the silent-fallback paths in the train step's
    output pinning (ADVICE r3: a skipped pin reintroduces the ZERO1
    recompile/layout drift with no signal). Once per distinct reason."""
    import warnings

    if message not in _UNPINNED_WARNED:
        _UNPINNED_WARNED.add(message)
        warnings.warn(message, stacklevel=3)


class DynamicLossScale(struct.PyTreeNode):
    """fp16 dynamic loss-scale state — the GradScaler analog (reference
    `utils/modeling.py:2054` `get_grad_scaler` + overflow-skip in
    `optimizer.py:162-176`), carried functionally inside :class:`TrainState`
    so the whole scaler lives in the compiled step.

    Semantics per step: grads are taken of ``loss * scale`` and unscaled;
    if any gradient is non-finite the parameter/optimizer update is skipped
    and ``scale *= backoff_factor``; after ``growth_interval`` consecutive
    finite steps ``scale *= growth_factor``.
    """

    scale: jax.Array  # f32 scalar
    growth_counter: jax.Array  # i32 scalar
    growth_factor: float = struct.field(pytree_node=False, default=2.0)
    backoff_factor: float = struct.field(pytree_node=False, default=0.5)
    growth_interval: int = struct.field(pytree_node=False, default=2000)

    @classmethod
    def create(cls, init_scale: float = 2.0**15, **kwargs: Any) -> "DynamicLossScale":
        return cls(
            scale=jnp.asarray(init_scale, jnp.float32),
            growth_counter=jnp.zeros((), jnp.int32),
            **kwargs,
        )


class TrainState(struct.PyTreeNode):
    """Functional train state: the pytree the jitted step transforms.

    Mirrors `flax.training.train_state.TrainState` in shape; owned by the
    framework so sharding/checkpoint logic controls its layout.
    ``loss_scale`` is None except under fp16 mixed precision (None is an
    empty pytree node, so every existing path is unaffected).
    """

    step: jax.Array
    params: Any
    opt_state: Any
    apply_fn: Callable = struct.field(pytree_node=False, default=None)
    tx: optax.GradientTransformation = struct.field(pytree_node=False, default=None)
    loss_scale: Any = None

    @classmethod
    def create(cls, *, params: Any, tx: optax.GradientTransformation, apply_fn: Callable | None = None) -> "TrainState":
        return cls(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=tx.init(params),
            apply_fn=apply_fn,
            tx=tx,
        )


def _specs_equal(a: Any, b: Any) -> bool:
    """Leaf-wise PartitionSpec equality between two spec trees (is_leaf
    guard because PartitionSpec is tuple-like and would be flattened into
    its entries otherwise). Used to verify an elastic mesh resize preserves
    every leaf's layout."""
    is_spec = lambda x: isinstance(x, PartitionSpec)  # noqa: E731
    la = jax.tree_util.tree_flatten(a, is_leaf=is_spec)[0]
    lb = jax.tree_util.tree_flatten(b, is_leaf=is_spec)[0]
    return len(la) == len(lb) and all(x == y for x, y in zip(la, lb))


def global_norm(tree: Any) -> jax.Array:
    leaves = [jnp.sum(jnp.square(x.astype(jnp.float32))) for x in jax.tree.leaves(tree)]
    return jnp.sqrt(jnp.sum(jnp.stack(leaves)))


class Accelerator:
    """Single entry point: mesh + shardings + compiled SPMD train step."""

    def __init__(
        self,
        *,
        mixed_precision: str | None = None,  # None -> ATX_MIXED_PRECISION env or "no"
        gradient_accumulation_steps: int = 1,
        gradient_accumulation_plugin: GradientAccumulationPlugin | None = None,
        mesh_config: MeshConfig | None = None,
        strategy: Any = None,
        sharding_rules: Sequence[tuple[str, PartitionSpec]] = (),
        max_grad_norm: float | None = None,
        max_grad_value: float | None = None,
        loss_scale_config: dict[str, Any] | None = None,
        dataloader_config: DataLoaderConfiguration | None = None,
        project_config: ProjectConfiguration | None = None,
        project_dir: str | None = None,
        log_with: Any = None,
        seed: int | None = None,
    ) -> None:
        from .utils.dataclasses import TensorParallelPlugin

        if isinstance(strategy, TensorParallelPlugin) and (strategy.tp_size or 1) > 1:
            # The plugin's tp_size is a mesh request: build (or validate) a
            # mesh whose `tensor` axis matches it, the way the reference's TP
            # plugin sizes its device sub-group (`utils/dataclasses.py:1863`).
            if mesh_config is None and MeshConfig.from_env() is None:
                mesh_config = MeshConfig(tensor=strategy.tp_size)
        self.state = AcceleratorState(mesh_config=mesh_config, mixed_precision=mixed_precision)
        if (
            isinstance(strategy, TensorParallelPlugin)
            and (strategy.tp_size or 1) > 1
            and self.state.mesh.shape[TENSOR_AXIS] != strategy.tp_size
        ):
            raise ValueError(
                f"TensorParallelPlugin(tp_size={strategy.tp_size}) does not "
                f"match the active mesh's tensor axis "
                f"({self.state.mesh.shape[TENSOR_AXIS]}); size the mesh's "
                "`tensor` axis to tp_size (MeshConfig(tensor=...) / "
                "ATX_MESH_TENSOR)."
            )
        self.process_state = ProcessState()
        if gradient_accumulation_plugin is None:
            gradient_accumulation_plugin = GradientAccumulationPlugin(
                num_steps=gradient_accumulation_steps if gradient_accumulation_steps > 1 else None
            )
        self.gradient_state = GradientState(gradient_accumulation_plugin.num_steps)
        self.gradient_accumulation_plugin = gradient_accumulation_plugin
        self.policy = MixedPrecisionPolicy.from_precision(self.state.mixed_precision)
        if strategy is None:
            # Launcher env contract (ATX_SHARDING_STRATEGY) fallback.
            import os

            strategy = os.environ.get("ATX_SHARDING_STRATEGY") or None
            if strategy in ("DATA_PARALLEL",):
                strategy = None  # the default; avoid requiring rules
        self.strategy = ShardingStrategy.resolve(strategy, rules=tuple(sharding_rules))
        self.max_grad_norm = max_grad_norm
        self.max_grad_value = max_grad_value
        self._loss_scale_config = dict(loss_scale_config or {})
        self.dataloader_config = dataloader_config or DataLoaderConfiguration()
        # Launcher env contract fallbacks (`commands/launch.py build_child_env`
        # forwards the config file's tracker/project knobs as ATX_*), same
        # pattern as the mesh/strategy env reads.
        import os

        if project_dir is None and project_config is None:
            project_dir = os.environ.get("ATX_PROJECT_DIR") or None
        self.project_config = project_config or ProjectConfiguration(project_dir=project_dir)
        self.rng = _set_seed(seed) if seed is not None else jax.random.PRNGKey(0)
        self.trackers: list[Any] = []
        if log_with is None and os.environ.get("ATX_LOG_WITH"):
            log_with = [
                t.strip() for t in os.environ["ATX_LOG_WITH"].split(",") if t.strip()
            ]
        self.log_with = log_with
        # Preemption safety (resilience/preemption.py): trap SIGTERM so a
        # spot reclaim / maintenance notice becomes an emergency checkpoint
        # at the next step boundary instead of lost work. Opt out with
        # ATX_PREEMPTION_HANDLER=0 (the handler is main-thread-only and
        # idempotent, so repeated Accelerator constructions are fine).
        from .utils.environment import parse_flag_from_env

        if parse_flag_from_env("ATX_PREEMPTION_HANDLER", True):
            from . import resilience

            resilience.install_preemption_handler()
        # GCE maintenance-event poller (resilience/gce.py): opt-in via
        # ATX_GCE_PREEMPT_POLL_SECS — catches metadata preemption notices
        # that arrive before (or without) the SIGTERM.
        from . import resilience as _resilience

        self._gce_poller = _resilience.maintenance_poller_from_env()
        # Durable checkpoint replication (resilience/replicate.py): opt-in
        # via ATX_REPLICATE_URL — a background thread mirrors each committed
        # checkpoint into the object store; None when replication is off.
        self._replicator = _resilience.replicator_from_env()
        # Peer-health watchdog (resilience/health.py): opt-in via
        # ATX_HEALTH_BEAT_SECS — collective-free heartbeats through the
        # checkpoint root (or the replicate store) flag a dead peer in
        # seconds and route the survivors onto the emergency-save +
        # exit-75 elastic path. None when disabled.
        self._health = None
        try:
            from . import checkpointing as _ckpt

            _health_root = _ckpt.checkpoint_root(self)
        except Exception:
            _health_root = None
        self._health = _resilience.health_from_env(
            root=_health_root,
            store=self._replicator.store if self._replicator is not None else None,
            process_index=self.process_index,
            num_processes=self.num_processes,
        )
        if self._health is not None:
            self._health.start()
        # Shrink/grow-in-place (resilience/elastic.py): opt-in via
        # ATX_ELASTIC_SHRINK — on health escalation or a devices-file
        # retarget, survivors agree on a reduced topology and reshard live
        # state in memory at the next step entry instead of relaunching.
        self._elastic = _resilience.elastic_controller_from_env(
            root=_health_root,
            store=self._replicator.store if self._replicator is not None else None,
            health=self._health,
            process_index=self.process_index,
            num_processes=self.num_processes,
            host_devices=jax.local_device_count(),
            total_devices=self.mesh.devices.size,
        )
        self._topology_callbacks: list[Callable] = []
        self._mesh_epoch = 0
        self._elastic_timer: tuple[int, str, float] | None = None
        self._preemption_exit_started = False
        self._preemption_sync_calls = 0
        self._flag_tensor: jax.Array | None = None
        self._checkpoint_registry: list[Any] = []
        self._param_specs: Any = None
        self._opt_specs: Any = None
        self._opt_host_shardings: Any = None
        self._dataloaders: list[DataLoader] = []
        self._train_steps: dict[int, Callable] = {}

    # ----------------------------------------------------------- properties
    @property
    def mesh(self) -> Mesh:
        return self.state.mesh

    @property
    def num_processes(self) -> int:
        return self.process_state.num_processes

    @property
    def process_index(self) -> int:
        return self.process_state.process_index

    @property
    def is_main_process(self) -> bool:
        return self.process_state.is_main_process

    @property
    def is_local_main_process(self) -> bool:
        return self.process_state.is_local_main_process

    @property
    def is_last_process(self) -> bool:
        return self.process_state.is_last_process

    @property
    def device(self) -> jax.Device:
        return self.process_state.device

    @property
    def use_distributed(self) -> bool:
        return self.process_state.use_distributed

    @property
    def mixed_precision(self) -> str:
        return self.state.mixed_precision

    @property
    def gradient_accumulation_steps(self) -> int:
        return self.gradient_state.num_steps

    @property
    def sync_gradients(self) -> bool:
        # Accumulation happens inside the compiled step; every outer step is a
        # sync step (reference `_do_sync`, accelerator.py:1090-1097, made moot).
        return True

    @property
    def data_parallel_size(self) -> int:
        return data_parallel_size(self.mesh)

    # ------------------------------------------------------------- process
    def print(self, *args: Any, **kwargs: Any) -> None:
        self.process_state.print(*args, **kwargs)

    def wait_for_everyone(self) -> None:
        self.process_state.wait_for_everyone()

    def split_between_processes(self, inputs: Any, apply_padding: bool = False):
        return self.process_state.split_between_processes(inputs, apply_padding)

    def on_main_process(self, f: Callable) -> Callable:
        return self.process_state.on_main_process(f)

    def on_local_main_process(self, f: Callable) -> Callable:
        return self.process_state.on_local_main_process(f)

    def main_process_first(self):
        return self.process_state.main_process_first()

    # -------------------------------------------------------------- prepare
    def prepare(self, *args: Any, lint: str | None = None) -> Any:
        """Polymorphic prepare (reference `prepare`, `accelerator.py:1283`).

        Dispatch per object type (`_prepare_one`, reference :1266-1281):
        `DataLoader` -> mesh-bound loader; `TrainState` -> sharded onto the
        mesh; optax `GradientTransformation` and schedules pass through
        (they live inside the jitted step). Returns objects in input order.

        ``lint`` runs the ahead-of-time sharding analyzer (ATX1xx family,
        docs/static_analysis.md) over each TrainState's planned specs
        BEFORE any buffer moves: ``"warn"`` surfaces findings as
        `AnalysisWarning`s, ``"error"`` raises `LintError` on
        error-severity findings (e.g. a spec axis missing from the mesh),
        ``"off"`` (default) skips. The ``ATX_LINT`` env var supplies the
        default so a launcher can turn it on fleet-wide.
        """
        mode = self._resolve_lint_mode(lint)
        prepared = tuple(self._prepare_one(a, lint=mode) for a in args)
        return prepared[0] if len(prepared) == 1 else prepared

    def _prepare_one(self, obj: Any, lint: str = "off") -> Any:
        if isinstance(obj, DataLoader):
            return self._prepare_data_loader_obj(obj)
        if isinstance(obj, TrainState):
            return self.prepare_train_state(obj, lint=lint)
        return obj

    @staticmethod
    def _resolve_lint_mode(lint: str | None) -> str:
        import os

        mode = lint if lint is not None else os.environ.get("ATX_LINT") or "off"
        if mode not in ("off", "warn", "error"):
            raise ValueError(
                f"lint={mode!r}: expected 'off', 'warn', or 'error' "
                "(or unset ATX_LINT)"
            )
        return mode

    def _dispatch_lint(self, report: Any, mode: str) -> None:
        """Route lint findings per mode: raise on errors under "error",
        everything else becomes an `AnalysisWarning`."""
        import warnings

        from .analysis import AnalysisWarning, LintError, Severity

        if mode == "error" and report.has_errors:
            raise LintError(report.findings)
        for finding in report.filter(Severity.WARNING):
            warnings.warn(finding.format(), AnalysisWarning, stacklevel=3)

    def _prepare_data_loader_obj(self, dl: DataLoader) -> DataLoader:
        dl._rebind(self.mesh, self.dataloader_config)
        self._dataloaders.append(dl)
        return dl

    def prepare_data_loader(
        self,
        dataset: Any,
        batch_size: int | None = None,
        *,
        shuffle: bool | None = None,
        seed: int | None = None,
        drop_last: bool | None = None,
        collate_fn: Callable | None = None,
        spec: PartitionSpec | None = None,
    ) -> DataLoader:
        """None for batch_size/shuffle/drop_last means "default" (1 / False /
        False) — or, when ``dataset`` is a torch DataLoader, "inherit from
        it"; explicit values always win over inherited ones."""
        from .data.torch_interop import is_torch_dataloader, unwrap_torch_dataloader

        if is_torch_dataloader(dataset):
            # Reference-style migration path: hand in the torch DataLoader,
            # get the framework loader over the same dataset back (the torch
            # sampler is replaced by the sharded seeded one, exactly as the
            # reference substitutes its BatchSamplerShard). A collate_fn
            # passed HERE receives raw torch samples; its output is
            # converted tensor->numpy.
            torch_cfg = unwrap_torch_dataloader(
                dataset, has_user_collate=collate_fn is not None
            )
            dataset = torch_cfg["dataset"]
            batch_size = batch_size if batch_size is not None else torch_cfg["batch_size"]
            shuffle = shuffle if shuffle is not None else torch_cfg["shuffle"]
            drop_last = drop_last if drop_last is not None else torch_cfg["drop_last"]
            seed = seed if seed is not None else torch_cfg["seed"]
            if collate_fn is not None:
                from .data.torch_interop import to_numpy as _to_np

                collate_fn = (lambda samples, _c=collate_fn: _to_np(_c(samples)))
            else:
                collate_fn = torch_cfg["collate_fn"]
        dl = DataLoader(
            dataset,
            batch_size if batch_size is not None else 1,
            shuffle=bool(shuffle),
            seed=seed if seed is not None else 0,
            drop_last=bool(drop_last),
            collate_fn=collate_fn,
            mesh=self.mesh,
            spec=spec,
            config=self.dataloader_config,
        )
        self._dataloaders.append(dl)
        return dl

    # ------------------------------------------------------- state creation
    def _resolve_specs(self, params_shapes: Any, tx: optax.GradientTransformation) -> tuple[Any, Any]:
        param_specs = infer_param_specs(params_shapes, self.mesh, self.strategy)
        opt_shapes = jax.eval_shape(tx.init, params_shapes)
        opt_specs = infer_opt_specs(opt_shapes, params_shapes, param_specs, self.mesh, self.strategy)
        self._param_specs, self._opt_specs = param_specs, opt_specs
        return param_specs, opt_specs

    def state_shardings(self, state_shapes: "TrainState") -> "TrainState":
        """TrainState-shaped pytree of NamedShardings (for jit out_shardings)."""
        replicated = NamedSharding(self.mesh, PartitionSpec())
        opt_sh = getattr(self, "_opt_host_shardings", None)
        return TrainState(
            step=replicated,
            params=to_named_shardings(self._param_specs, self.mesh),
            opt_state=opt_sh
            if opt_sh is not None
            else to_named_shardings(self._opt_specs, self.mesh),
            apply_fn=state_shapes.apply_fn,
            tx=state_shapes.tx,
            loss_scale=jax.tree.map(lambda _: replicated, state_shapes.loss_scale),
        )

    def _maybe_loss_scale(self) -> DynamicLossScale | None:
        """fp16 compute requires a dynamic loss scaler (fp16's 5-bit exponent
        underflows real gradients); bf16/fp32 need none. ``loss_scale_config``
        (init_scale / growth_factor / backoff_factor / growth_interval)
        overrides the GradScaler-equivalent defaults — e.g. a ds_config's
        fp16 block maps onto it (`utils/ds_config.py`)."""
        if self.policy.compute_dtype == jnp.float16:
            return jax.device_put(
                DynamicLossScale.create(**self._loss_scale_config),
                NamedSharding(self.mesh, PartitionSpec()),
            )
        return None

    def _offload_opt_placement(self, tx: Any, opt_shapes_fn: Callable, opt_sh: Any) -> Any:
        """Apply the offload_optimizer placement policy to the optimizer
        shardings: pinned-host float moments when the backend supports it
        (and the optimizer is offload-aware), a loud fallback otherwise.
        Records the host shardings for the train step's streaming path."""
        self._opt_host_shardings = None
        if getattr(self.strategy, "offload_optimizer_device", None) == "nvme":
            # The run configuration (e.g. a ds_config with
            # offload_optimizer.device='nvme') requested the DISK tier,
            # which rides the optimizer object — a plain optax optimizer
            # here would silently train with device-resident moments, the
            # exact downgrade the 'cpu' tier already refuses.
            from .parallel.disk_offload import DiskOffloadedAdamW

            if not isinstance(tx, DiskOffloadedAdamW):
                raise ValueError(
                    "offload_optimizer.device='nvme' was requested but the "
                    "optimizer is not disk-offloaded; use "
                    "disk_offloaded_adamw(..., offload_dir=<nvme_path>) (or "
                    "optax_from_deepspeed_config, which builds it from the "
                    "same ds_config) instead of a plain optax transformation."
                )
        if not self.strategy.offload_optimizer:
            return opt_sh
        from .parallel import host_offload as _ho

        if not _ho.host_offload_supported():
            _ho.warn_host_offload_unsupported()
            return opt_sh
        if not isinstance(tx, _ho.HostOffloadedAdamW):
            raise ValueError(
                "offload_optimizer requires an offload-aware optimizer: use "
                "accelerate_tpu.host_offloaded_adamw(...) instead of a plain "
                "optax transformation — the streamed update must know the "
                "optimizer's math (the DeepSpeedCPUAdam requirement, "
                "reference utils/deepspeed.py:29)."
            )
        # ZeRO-Offload analog: float moments live in pinned host RAM and
        # never materialize whole in HBM.
        opt_sh = _ho.host_opt_shardings(opt_shapes_fn(), opt_sh)
        self._opt_host_shardings = opt_sh
        return opt_sh

    def create_train_state(
        self,
        init_fn: Callable[[jax.Array], Any] | Any,
        tx: optax.GradientTransformation,
        *,
        apply_fn: Callable | None = None,
        rng: jax.Array | None = None,
    ) -> TrainState:
        """Build a sharded TrainState directly on the mesh.

        ``init_fn`` is either `(rng) -> params` (jit-compiled with sharded
        out-shardings so huge models initialize *already sharded*, never
        materializing unsharded on one device — the meta-device-init analog,
        reference `big_modeling.py:58`) or a concrete params pytree.
        """
        rng = rng if rng is not None else self.rng
        if callable(init_fn):
            params_shapes = jax.eval_shape(init_fn, rng)
            param_specs, opt_specs = self._resolve_specs(params_shapes, tx)
            param_sh = to_named_shardings(param_specs, self.mesh)
            params = jax.jit(init_fn, out_shardings=param_sh)(rng)
        else:
            params_shapes = jax.eval_shape(lambda: init_fn)
            param_specs, opt_specs = self._resolve_specs(params_shapes, tx)
            params = shard_pytree(init_fn, param_specs, self.mesh)
        if self.policy.param_dtype is not None:
            # Explicit master-param dtype (policy.param_dtype docstring):
            # cast float leaves; ints (embedding tables are float, token ids
            # never live in params, but quantized int8 leaves do) stay put.
            pd = self.policy.param_dtype
            params = jax.tree.map(
                lambda x: x.astype(pd)
                if jnp.issubdtype(x.dtype, jnp.floating)
                else x,
                params,
            )
        opt_sh = self._offload_opt_placement(
            tx, lambda: jax.eval_shape(tx.init, params),
            to_named_shardings(opt_specs, self.mesh),
        )
        opt_state = jax.jit(tx.init, out_shardings=opt_sh)(params)
        # The step counter must be mesh-replicated like every other scalar in
        # the state: a single-device scalar here gives the first jitted step
        # a different input layout than every later one (one wasted compile).
        replicated = NamedSharding(self.mesh, PartitionSpec())
        return TrainState(
            step=jax.device_put(jnp.zeros((), jnp.int32), replicated),
            params=params,
            opt_state=opt_state,
            apply_fn=apply_fn,
            tx=tx,
            loss_scale=self._maybe_loss_scale(),
        )

    def prepare_train_state(self, state: TrainState, *, lint: str | None = None) -> TrainState:
        """Shard an existing (host or single-device) TrainState onto the mesh.

        ``lint`` ("off"|"warn"|"error", default from ``ATX_LINT``) runs the
        sharding analyzer over the planned specs first — a bad spec is
        caught here, before GiBs start moving, not three hours into a pod
        run (see `prepare`)."""
        from .parallel.host_offload import place_opt_state as _ho_place

        mode = self._resolve_lint_mode(lint)
        if mode != "off":
            from . import analysis

            report = analysis.lint_specs(
                jax.eval_shape(lambda: state.params),
                self.mesh,
                strategy=self.strategy,
                opt_shapes=jax.eval_shape(lambda: state.opt_state),
                target="prepare_train_state",
            )
            # ATX_LINT_PROCESSES=N (N >= 2) additionally proves the planned
            # specs are process-independent: the same inference replayed
            # under each simulated process_index must agree (ATX501).
            import os

            procs = int(os.environ.get("ATX_LINT_PROCESSES", "1") or "1")
            if procs >= 2:
                from .analysis import rules_multihost

                shapes = jax.eval_shape(lambda: state.params)
                report.findings.extend(
                    rules_multihost.spec_consistency_findings(
                        lambda: infer_param_specs(shapes, self.mesh, self.strategy),
                        procs,
                    )
                )
            self._dispatch_lint(report, mode)

        params_shapes = jax.eval_shape(lambda: state.params)
        param_specs, opt_specs = self._resolve_specs(params_shapes, state.tx)
        loss_scale = state.loss_scale
        if loss_scale is None:
            loss_scale = self._maybe_loss_scale()
        else:
            # A restored scaler may carry single-device layout; replicate it
            # like every other state scalar or the first step recompiles.
            loss_scale = jax.device_put(
                loss_scale, NamedSharding(self.mesh, PartitionSpec())
            )
        opt_sh = self._offload_opt_placement(
            state.tx, lambda: jax.eval_shape(lambda: state.opt_state),
            to_named_shardings(opt_specs, self.mesh),
        )
        return state.replace(
            step=jax.device_put(
                state.step, NamedSharding(self.mesh, PartitionSpec())
            ),
            params=shard_pytree(state.params, param_specs, self.mesh),
            # Chunked pooled placement (host-offloaded moments are the big
            # case: GiBs of fp32 headed for pinned host RAM).
            opt_state=_ho_place(state.opt_state, opt_sh),
            loss_scale=loss_scale,
        )

    def unwrap(self, state: TrainState) -> Any:
        """Reference `unwrap_model` (`accelerator.py:2745`): the raw params."""
        return state.params

    unwrap_model = unwrap

    # ------------------------------------------------------------ scheduler
    def prepare_scheduler(self, schedule: Callable[[Any], Any]) -> Callable[[Any], Any]:
        """Adapt an optax schedule to gradient accumulation (reference
        `AcceleratedScheduler`, `scheduler.py:62`).

        With ``adjust_scheduler=True`` (the plugin default) the reference
        advances the LR schedule once per *batch* even on non-sync
        accumulation steps, so a schedule denominated in batches completes
        on time. Optax schedules count optimizer updates — which advance
        ``num_steps``× slower under accumulation — so the returned schedule
        evaluates the original at ``count * num_steps``. The schedule you
        pass in must therefore be denominated in *microbatches* (reference
        batches): with ``total_updates`` optimizer steps planned that is
        ``total_updates * num_steps``, NOT ``len(loader) * epochs`` (a
        framework dataloader batch is the whole accumulation window). Pass
        the result as the ``learning_rate`` of your optax optimizer::

            microbatches = total_updates * accelerator.gradient_accumulation_steps
            sched = accelerator.prepare_scheduler(
                optax.cosine_decay_schedule(3e-4, decay_steps=microbatches))
            tx = optax.adamw(learning_rate=sched)

        With ``adjust_scheduler=False`` (or no accumulation) the schedule is
        returned unchanged.
        """
        accum = self.gradient_state.num_steps
        if accum <= 1 or not self.gradient_accumulation_plugin.adjust_scheduler:
            return schedule

        def adjusted(count):
            return schedule(count * accum)

        return adjusted

    # ----------------------------------------------------------- train step
    def make_train_step(
        self,
        loss_fn: Callable[..., Any],
        *,
        has_aux: bool = False,
        donate: bool = True,
        extra_metrics_fn: Callable[[Any, Any], dict[str, jax.Array]] | None = None,
    ) -> Callable[[TrainState, Any], tuple[TrainState, dict[str, jax.Array]]]:
        """Compile the full training step.

        ``loss_fn(params, batch, rng) -> loss`` (or ``(loss, aux)`` with
        ``has_aux``). The returned callable maps
        ``(state, batch) -> (state, metrics)`` and internally:

        1. splits the global batch into `gradient_accumulation_steps`
           microbatches and `lax.scan`s gradients (reference `accumulate`,
           `accelerator.py:1116`; DDP ``no_sync`` dance is unnecessary — one
           compiled step has exactly one gradient reduction);
        2. computes in `policy.compute_dtype` with fp32 master params
           (autocast analog, :1462-1473) — gradients come out fp32 because
           autodiff flows through the cast;
        3. clips by global norm when `max_grad_norm` is set (:2485);
        4. applies the optax update; LR schedules live in the optax chain
           (the `AcceleratedScheduler` skip-on-overflow logic is bf16-moot).
        """
        accum = self.gradient_state.num_steps
        policy = self.policy
        max_grad_norm = self.max_grad_norm
        max_grad_value = self.max_grad_value
        use_scaler = policy.compute_dtype == jnp.float16
        # Capture the planned specs NOW (create_train_state time), not at
        # trace time: a later create_train_state for a second model would
        # overwrite self._param_specs and this step would pin the wrong
        # layout (or crash on tree mismatch) when it finally traces.
        planned_param_specs = getattr(self, "_param_specs", None)
        planned_opt_specs = getattr(self, "_opt_specs", None)
        # Host-offloaded moments (create_train_state decided placement):
        # the step moves them host->HBM right before the update and back
        # after, all inside the jit so XLA overlaps the DMAs with compute.
        opt_host_shardings = getattr(self, "_opt_host_shardings", None)
        if opt_host_shardings is not None and use_scaler:
            raise ValueError(
                "offload_optimizer with fp16 dynamic loss scaling is not "
                "supported (the overflow-skip select would have to span "
                "memory spaces); use bf16 mixed precision."
            )
        # Non-finite training guard (opt-in, ATX_NAN_GUARD): the compiled
        # step skips the optimizer update via a pure lax.cond whenever the
        # loss or any gradient is non-finite — no host sync on the happy
        # path. The host side counts consecutive skips off the returned
        # metrics (drained only when .is_ready(), so dispatch stays async)
        # and aborts with NonFiniteGuardError after
        # ATX_NAN_GUARD_MAX_CONSECUTIVE (default 3) bad steps in a row.
        from .utils.environment import get_int_from_env, parse_flag_from_env

        nan_guard = parse_flag_from_env("ATX_NAN_GUARD", False)
        nan_guard_budget = max(
            1, get_int_from_env(("ATX_NAN_GUARD_MAX_CONSECUTIVE",), 3)
        )
        if nan_guard and opt_host_shardings is not None:
            raise ValueError(
                "ATX_NAN_GUARD with offload_optimizer is not supported (the "
                "skip-update cond would have to span memory spaces, like the "
                "fp16 overflow select); disable one of the two."
            )

        def _pin(tree: Any, spec_tree: Any) -> Any:
            """Constrain `tree` to its planned shardings; skipped when no
            plan exists or the structures disagree (a state this step was
            not planned for). The skip warns once — a silently unpinned
            output regresses the ZERO1 layout/recompile fix without any
            signal."""
            if spec_tree is None:
                _warn_unpinned_once(
                    "make_train_step has no planned shardings to pin outputs "
                    "to (create_train_state was not called on this "
                    "Accelerator); output layouts are left to the "
                    "partitioner, which may recompile or change the "
                    "strategy's memory story."
                )
                return tree
            is_spec = lambda x: isinstance(x, PartitionSpec)
            if jax.tree.structure(tree) != jax.tree.structure(spec_tree, is_leaf=is_spec):
                _warn_unpinned_once(
                    "make_train_step's planned shardings do not match the "
                    "state actually passed to the step (different model?); "
                    "outputs are left unpinned."
                )
                return tree
            return jax.tree.map(
                jax.lax.with_sharding_constraint,
                tree,
                to_named_shardings(spec_tree, self.mesh),
            )

        def compute_loss(params: Any, batch: Any, rng: jax.Array, scale: jax.Array):
            cparams = policy.cast_for_compute(params)
            cbatch = policy.cast_for_compute(batch)
            # Under fp8, the model traces with matmuls lowered to scaled-fp8
            # contractions (ops/fp8.py); the mode is read at trace time, so
            # the compiled step bakes it in.
            with _fp8.fp8_matmuls(policy.fp8):
                out = loss_fn(cparams, cbatch, rng)
                if policy.fp8 and _fp8.fp8_hits() == 0:
                    _warn_fp8_noop()
            if has_aux:
                loss, aux = out
            else:
                loss, aux = out, None
            loss = loss.astype(jnp.float32)
            # Differentiate the SCALED loss (fp16 grads underflow otherwise);
            # scale == 1.0 outside fp16, so this is the identity there.
            return loss * scale, (loss, aux)

        grad_fn = jax.value_and_grad(compute_loss, has_aux=True)

        def accumulated_grads(params, batch, rng, scale):
            """(grads, loss, reduced aux) — the one microbatch-accumulation
            pipeline, shared by the monolithic step and the disk-tier grad
            pass so the two cannot drift."""
            if accum > 1:
                def reshape(x):
                    b = x.shape[0]
                    if b % accum != 0:
                        raise ValueError(
                            f"Global batch size {b} is not divisible by "
                            f"gradient_accumulation_steps={accum}; adjust the "
                            "dataloader batch size or the accumulation steps."
                        )
                    return x.reshape((accum, b // accum) + x.shape[1:])

                microbatches = jax.tree.map(reshape, batch)

                def scan_body(carry, xs):
                    mb, mb_idx = xs
                    g_acc, l_acc = carry
                    # Distinct rng per microbatch: otherwise dropout masks are
                    # identical across the accumulation window.
                    (_, (loss, aux)), grads = grad_fn(
                        params, mb, jax.random.fold_in(rng, mb_idx), scale
                    )
                    g_acc = jax.tree.map(jnp.add, g_acc, grads)
                    return (g_acc, l_acc + loss), aux

                zero_grads = jax.tree.map(
                    lambda x: jnp.zeros(x.shape, jnp.float32), params
                )
                (grads, loss_sum), aux = jax.lax.scan(
                    scan_body,
                    (zero_grads, jnp.zeros((), jnp.float32)),
                    (microbatches, jnp.arange(accum)),
                )
                grads = jax.tree.map(lambda g: g / accum, grads)
                loss = loss_sum / accum
                # lax.scan stacked aux along the accumulation axis; reduce it
                # so extra_metrics_fn sees the same values regardless of the
                # accumulation setting: mean for float metrics, sum for
                # integer counters (a count over the full batch).
                if aux is not None:
                    aux = jax.tree.map(
                        lambda x: jnp.mean(x, axis=0)
                        if jnp.issubdtype(x.dtype, jnp.inexact)
                        else jnp.sum(x, axis=0),
                        aux,
                    )
                return grads, loss, aux
            (_, (loss, aux)), grads = grad_fn(params, batch, rng, scale)
            return grads, loss, aux

        def step_fn(state: TrainState, batch: Any) -> tuple[TrainState, dict[str, jax.Array]]:
            rng = jax.random.fold_in(self.rng, state.step)
            scale = state.loss_scale.scale if use_scaler else jnp.float32(1.0)
            grads, loss, aux = accumulated_grads(state.params, batch, rng, scale)

            # Loss math stays fp32 throughout; output_dtype only changes the
            # dtype the metric is *reported* in.
            metrics: dict[str, jax.Array] = {
                "loss": loss
                if policy.output_dtype is None
                else loss.astype(policy.output_dtype)
            }
            guard_finite = None
            if nan_guard and not use_scaler:
                # Raw loss + grads, BEFORE clipping: a clip can turn inf into
                # a large finite number and mask the divergence signal.
                guard_finite = jnp.isfinite(loss) & jnp.all(
                    jnp.stack(
                        [jnp.all(jnp.isfinite(g)) for g in jax.tree.leaves(grads)]
                    )
                )
            if use_scaler:
                grads = jax.tree.map(lambda g: g / scale, grads)
                finite = jnp.all(
                    jnp.stack(
                        [jnp.all(jnp.isfinite(g)) for g in jax.tree.leaves(grads)]
                    )
                )
                if nan_guard:
                    # The scaler's select already skips on non-finite grads;
                    # the guard adds the loss itself (a NaN loss with finite
                    # grads is still divergence) and the abort budget.
                    guard_finite = finite & jnp.isfinite(loss)
                # Zero non-finite grads so the (discarded) optimizer update
                # below computes on clean numbers either way.
                grads = jax.tree.map(
                    lambda g: jnp.where(finite, g, jnp.zeros_like(g)), grads
                )
            if max_grad_value is not None:
                # clip_grad_value_ analog (reference accelerator.py:2523):
                # elementwise clamp, applied BEFORE norm clipping like a
                # torch loop calling both would.
                grads = jax.tree.map(
                    lambda g: jnp.clip(g, -max_grad_value, max_grad_value), grads
                )
            grad_scale = None
            if max_grad_norm is not None:
                gnorm = global_norm(grads)
                clip = jnp.minimum(1.0, max_grad_norm / (gnorm + 1e-6))
                if opt_host_shardings is None:
                    grads = jax.tree.map(lambda g: g * clip, grads)
                else:
                    # Folding the clip into the streamed per-layer update
                    # avoids materializing a scaled copy of every gradient
                    # (measured: 6 GiB of fp32 HLO temps at 1.6B).
                    grad_scale = clip
                metrics["grad_norm"] = gnorm
            if opt_host_shardings is not None:
                # Layer-streamed offloaded update (host_offload module
                # docstring): moments stay pinned-host; one layer's slices
                # at a time round-trip through HBM inside a lax.scan.
                from .parallel.host_offload import streaming_adamw_update

                updates, new_opt_state = streaming_adamw_update(
                    state.tx,
                    grads,
                    state.opt_state,
                    state.params,
                    planned_param_specs,
                    self.mesh,
                    grad_scale=grad_scale,
                )
                new_params = optax.apply_updates(state.params, updates)
            elif nan_guard:
                # Guarded update: a pure lax.cond keeps the whole optimizer
                # update off the trace when the step is bad — params and
                # opt-state pass through IDENTICALLY (no 0-update applied,
                # so stateful transforms like Adam moments don't advance on
                # garbage). The predicate is a device scalar; no host sync.
                def _apply_update(operand):
                    g, p, o = operand
                    upd, new_o = state.tx.update(g, o, p)
                    return optax.apply_updates(p, upd), new_o

                def _skip_update(operand):
                    _, p, o = operand
                    return p, o

                new_params, new_opt_state = jax.lax.cond(
                    guard_finite,
                    _apply_update,
                    _skip_update,
                    (grads, state.params, state.opt_state),
                )
            else:
                updates, new_opt_state = state.tx.update(
                    grads, state.opt_state, state.params
                )
                new_params = optax.apply_updates(state.params, updates)
            new_loss_scale = state.loss_scale
            if use_scaler:
                # Overflow: keep params/opt untouched, back the scale off.
                # Finite: apply, and grow the scale every `growth_interval`
                # consecutive finite steps (reference optimizer.py:162-176:
                # `scaler.step` skips on inf, `scaler.update` adjusts).
                keep_new = lambda new, old: jax.tree.map(
                    lambda n, o: jnp.where(finite, n, o), new, old
                )
                new_params = keep_new(new_params, state.params)
                new_opt_state = keep_new(new_opt_state, state.opt_state)
                ls = state.loss_scale
                counter = jnp.where(finite, ls.growth_counter + 1, 0)
                grow = counter >= ls.growth_interval
                new_scale = jnp.where(
                    finite,
                    jnp.where(grow, scale * ls.growth_factor, scale),
                    scale * ls.backoff_factor,
                )
                new_loss_scale = ls.replace(
                    scale=new_scale, growth_counter=jnp.where(grow, 0, counter)
                )
                metrics["loss_scale"] = new_scale
                metrics["grads_finite"] = finite
            if nan_guard:
                metrics["nonfinite_skipped"] = (~guard_finite).astype(jnp.int32)
            # Pin the updated params/opt-state to their PLANNED shardings.
            # Without this, jit is free to return them in whatever layout the
            # partitioner found cheapest for this program (e.g. ZERO1's
            # sharded-update output params came back sharded instead of
            # replicated) — which silently changes the strategy's memory
            # story AND forces a recompile when the state round-trips into
            # the next step with a different input layout.
            new_params = _pin(new_params, planned_param_specs)
            if opt_host_shardings is not None:
                # Explicit host placement IS the output pinning here.
                new_opt_state = jax.tree.map(
                    lambda x, s: jax.device_put(x, s),
                    new_opt_state,
                    opt_host_shardings,
                )
            else:
                new_opt_state = _pin(new_opt_state, planned_opt_specs)
            new_state = state.replace(
                step=state.step + 1,
                params=new_params,
                opt_state=new_opt_state,
                loss_scale=new_loss_scale,
            )
            if extra_metrics_fn is not None:
                metrics.update(extra_metrics_fn(new_state, aux))
            return new_state, metrics

        donate_args = (0,) if donate else ()
        jitted = jax.jit(step_fn, donate_argnums=donate_args)

        # ---- disk-tier optimizer offload (parallel/disk_offload.py): the
        # step splits into a compiled grad pass and a host-streamed update
        # against disk-resident moments, so it cannot ride the monolithic
        # jit above. Closures are built lazily on first use.
        _disk_jits: dict[str, Any] = {}

        def run_disk_step(state: TrainState, batch: Any):
            from .parallel.disk_offload import disk_streamed_update

            if use_scaler:
                raise ValueError(
                    "disk offload_optimizer with fp16 dynamic loss scaling "
                    "is not supported (the overflow-skip select would span "
                    "the host update); use bf16 mixed precision."
                )
            if nan_guard:
                raise ValueError(
                    "ATX_NAN_GUARD is not supported with disk-offloaded "
                    "optimizers (the update streams through the host outside "
                    "the compiled step, so there is no in-jit skip point); "
                    "disable one of the two."
                )
            if not all(
                l.is_fully_addressable for l in jax.tree.leaves(state.params)
            ):
                raise NotImplementedError(
                    "disk_offloaded_adamw streams grads through THIS host, so "
                    "it requires fully-addressable (single-process) params — "
                    "the DeepSpeed per-node NVMe-swap shape. For sharded "
                    "multi-process params use the pinned-host tier "
                    "(host_offloaded_adamw), whose update runs inside the "
                    "compiled SPMD program."
                )
            if "grad" not in _disk_jits:
                def grad_step(params, batch, step_idx):
                    rng = jax.random.fold_in(self.rng, step_idx)
                    grads, loss, aux = accumulated_grads(
                        params, batch, rng, jnp.float32(1.0)
                    )
                    metrics = {
                        "loss": loss
                        if policy.output_dtype is None
                        else loss.astype(policy.output_dtype)
                    }
                    if max_grad_value is not None:
                        grads = jax.tree.map(
                            lambda g: jnp.clip(g, -max_grad_value, max_grad_value),
                            grads,
                        )
                    gs = jnp.float32(1.0)
                    if max_grad_norm is not None:
                        gnorm = global_norm(grads)
                        gs = jnp.minimum(1.0, max_grad_norm / (gnorm + 1e-6))
                        metrics["grad_norm"] = gnorm
                    return grads, metrics, gs, aux

                _disk_jits["grad"] = jax.jit(grad_step)
                _disk_jits["apply"] = jax.jit(
                    lambda p, u: optax.apply_updates(p, u),
                    donate_argnums=(0,) if donate else (),
                )
            here = int(jax.device_get(state.step))
            if _disk_jits.get("next_step") != here:
                # First call, or the state's step jumped (a checkpoint was
                # restored mid-run): the memmaps are the optimizer
                # checkpoint, and pairing them with a state from any OTHER
                # step silently corrupts the bias correction (moments ahead
                # of the count). Steady-state steps skip the file read.
                # count() joins the overlapped flush from the previous step
                # first, so the guard judges completed moments.
                stored = state.tx.store.count()
                if stored is not None and stored != here:
                    raise ValueError(
                        f"disk-offloaded moments in {state.tx.store.dir!r} "
                        f"were last written at step {stored}, but the "
                        f"restored train state is at step {here}. Restore "
                        "the matching checkpoint, or point offload_dir at a "
                        "fresh directory to restart the optimizer."
                    )
            with use_mesh(self.mesh):
                grads, metrics, gs, aux = _disk_jits["grad"](
                    state.params, batch, state.step
                )
            count = here + 1
            _disk_jits["next_step"] = count
            grad_scale = (
                float(jax.device_get(gs)) if max_grad_norm is not None else None
            )
            updates = disk_streamed_update(
                state.tx, grads, state.params, count, grad_scale
            )
            del grads
            # Each update leaf lands directly in its param's sharding —
            # one flat device_put to the default device would commit the
            # whole tree to one chip on a multi-chip mesh. The transfer
            # engine streams the big stacked leaves in chunks from its
            # worker pool instead of serializing behind one Python-level
            # device_put per leaf.
            from .parallel.transfer import get_transfer_engine

            updates = get_transfer_engine().put_tree(
                updates, jax.tree.map(lambda p: p.sharding, state.params)
            ).result()
            with use_mesh(self.mesh):
                new_params = _disk_jits["apply"](state.params, updates)
            new_state = state.replace(
                step=state.step + 1,
                params=new_params,
                opt_state={"count": jnp.asarray(count, jnp.int32)},
            )
            if extra_metrics_fn is not None:
                metrics.update(extra_metrics_fn(new_state, aux))
            return new_state, metrics

        # NaN-guard host state: `pending` holds the nonfinite_skipped metric
        # of in-flight steps (device scalars, appended in dispatch order);
        # entries are folded into the consecutive-skip streak only once
        # .is_ready(), so the guard never blocks the async dispatch pipeline.
        _guard = {"pending": [], "streak": 0, "skipped_total": 0}

        def _drain_guard(block: bool = False) -> None:
            pending = _guard["pending"]
            while pending and (block or pending[0].is_ready()):
                skipped = int(jax.device_get(pending.pop(0)))
                _guard["skipped_total"] += skipped
                _guard["streak"] = _guard["streak"] + 1 if skipped else 0
                if _guard["streak"] >= nan_guard_budget:
                    from .telemetry import flight as _flight

                    _flight.dump_postmortem(
                        "nan_guard",
                        extra={
                            "streak": _guard["streak"],
                            "skipped_total": _guard["skipped_total"],
                            "budget": nan_guard_budget,
                        },
                    )
                    raise NonFiniteGuardError(
                        f"ATX_NAN_GUARD: {_guard['streak']} consecutive "
                        "training steps produced a non-finite loss or "
                        "gradients (budget ATX_NAN_GUARD_MAX_CONSECUTIVE="
                        f"{nan_guard_budget}; {_guard['skipped_total']} "
                        "skipped in total this run). Every bad step's "
                        "optimizer update was skipped, so the current state "
                        "is the last finite one — checkpoint it, then lower "
                        "the learning rate / inspect the input pipeline "
                        "before resuming."
                    )

        # Health-beat step hint: a host-side counter (seeded once from the
        # state, then incremented) so note_step never forces a device sync.
        _host_step = {"n": None}

        # ---- step telemetry (docs/observability.md). ATX_METRICS=0 removes
        # every hook; with it on (default) the hooks are host clocks + shape
        # math only — zero device syncs unless ATX_METRICS_SAMPLE_EVERY turns
        # the block_until_ready sampler on. Nothing here touches rng, step
        # math, or dispatch order, so losses are bit-identical either way.
        from . import telemetry as _telemetry
        from .utils import profiler as _profiler
        from .utils.environment import get_int_from_env as _get_int

        _stats: Any = None
        _stats_cell: dict[str, Any] = {"tokens": None, "abstract": None, "calls": 0}
        _metrics_log_every = 0
        _metrics_dir = ""
        if _telemetry.metrics_enabled():
            peak = _telemetry.peak_device_flops()
            peak_total = peak * jax.device_count() if peak else None

            def _flops_fn() -> float | None:
                abstract = _stats_cell["abstract"]
                if abstract is None:
                    return None
                compiled = lower(*abstract).compile()
                flops = _profiler.estimate_step_flops(compiled)
                return None if flops is None else flops * jax.device_count()

            _stats = _telemetry.StepStats(
                flops_fn=_flops_fn, peak_flops_total=peak_total
            )
            _metrics_log_every = _get_int(("ATX_METRICS_LOG_EVERY",), 0)
            _metrics_dir = os.environ.get("ATX_METRICS_DIR", "")

        def _stats_entry(state: TrainState, batch: Any) -> None:
            if _stats_cell["tokens"] is None:
                _stats_cell["tokens"] = _telemetry.tokens_in_batch(batch)
                if _stats.peak_flops_total:
                    _stats_cell["abstract"] = jax.tree.map(
                        lambda x: jax.ShapeDtypeStruct(
                            jnp.shape(x), jnp.result_type(x)
                        ),
                        (state, batch),
                    )
            _stats.on_entry(_stats_cell["tokens"])

        def _stats_dispatched(metrics: Any) -> None:
            _stats.on_dispatched(metrics, cache_size=jitted._cache_size())
            n = _stats_cell["calls"]
            if _metrics_log_every and n % _metrics_log_every == 0:
                if self.trackers:
                    self.log(_stats.latest(), step=n)
                if _metrics_dir:
                    _telemetry.write_snapshot(
                        _metrics_dir, process_index=self.process_index
                    )

        def run_step(state: TrainState, batch: Any):
            from . import resilience
            from .parallel.disk_offload import DiskOffloadedAdamW

            _stats_cell["calls"] += 1
            if _stats is not None:
                _stats_entry(state, batch)
            if nan_guard:
                _drain_guard()
                # Bound the undrained window so detection can't lag forever
                # behind a deep dispatch queue.
                if len(_guard["pending"]) > max(8, 2 * nan_guard_budget):
                    _drain_guard(block=True)
            if self._health is not None or self._elastic is not None:
                if _host_step["n"] is None:
                    _host_step["n"] = int(jax.device_get(state.step))
                else:
                    _host_step["n"] += 1
                if self._health is not None:
                    self._health.note_step(_host_step["n"])
            # Elastic shrink/grow check BEFORE the preemption boundary: a
            # successful in-place resize clears the health-escalated
            # preemption flag so the emergency-save + exit-75 machinery
            # below never fires; a failed one leaves the flag set and the
            # very next lines take the relaunch path as before.
            if self._elastic is not None:
                resized = self._maybe_elastic_resize(state, _host_step["n"])
                if resized is not None:
                    state = resized
            # Preemption boundary check at ENTRY, before any compute: the
            # input state is exactly the last completed step's output (whose
            # metrics the caller already has), so the emergency checkpoint
            # loses nothing and the resumed trajectory is bit-identical.
            # Multi-process, this is a COLLECTIVE (flag or-reduce): every
            # process participates every entry so the group agrees on the
            # exit step — one process acting on its local flag alone would
            # barrier against peers still in training-step collectives.
            self._maybe_emergency_exit(state)
            # Hang watchdog (ATX_WATCHDOG_SECS): heartbeat semantics — each
            # step ENTRY re-arms the countdown and it stays armed across the
            # call, because jax dispatches the compiled step asynchronously
            # (the call can return before the device work runs; a disarm
            # here would miss a wedged collective entirely). A wedge is
            # caught when the loop blocks fetching the step's metrics — or
            # wherever the process stalls — and no next step entry arrives
            # within the deadline. `end_training()` disarms.
            wd = resilience.watchdog_from_env()
            if wd is not None:
                wd.arm()
            if isinstance(state.tx, DiskOffloadedAdamW):
                new_state, metrics = run_disk_step(state, batch)
                if _stats is not None:
                    _stats_dispatched(None)
                return new_state, metrics
            # Trace (and run) under the ambient mesh so the model's
            # activation constraints (parallel.mesh.constrain_batch) bind
            # to this Accelerator's axes. While an XPlane capture is live the
            # step also enters StepTraceAnnotation so traces show numbered
            # steps (utils/profiler.maybe_step_annotation — a no-op context
            # otherwise).
            with use_mesh(self.mesh), _profiler.maybe_step_annotation(
                _stats_cell["calls"]
            ):
                new_state, metrics = jitted(state, batch)
            if self._elastic_timer is not None:
                # First step after an in-place resize: block on its output
                # (once) so the reported escalation -> first-step wall clock
                # covers real compute, not an async dispatch.
                self._report_elastic_latency(new_state)
            if nan_guard:
                _guard["pending"].append(metrics["nonfinite_skipped"])
            if _stats is not None:
                _stats_dispatched(metrics)
            return new_state, metrics

        def lower(*args: Any, **kwargs: Any):
            with use_mesh(self.mesh):
                return jitted.lower(*args, **kwargs)

        # Keep the jit surface the HLO-verification tooling relies on.
        run_step.lower = lower
        run_step._cache_size = jitted._cache_size
        # Telemetry read side (None when ATX_METRICS=0): bench and the
        # tracker glue read EMA'd step timing from here.
        run_step.step_stats = _stats
        # NaN-guard introspection: counters for tests/metrics, and a blocking
        # drain so a loop's last steps are judged before it declares success.
        run_step._nan_guard = _guard if nan_guard else None
        run_step.drain_nan_guard = (
            (lambda: _drain_guard(block=True)) if nan_guard else (lambda: None)
        )
        self._train_steps[id(run_step)] = jitted
        return run_step

    def make_eval_step(
        self, fn: Callable[[Any, Any], Any]
    ) -> Callable[[TrainState, Any], Any]:
        """Compile an inference/eval step ``fn(params, batch) -> outputs`` with
        params cast to the compute dtype."""
        policy = self.policy

        def eval_fn(state: TrainState, batch: Any) -> Any:
            with _fp8.fp8_matmuls(policy.fp8):
                return fn(policy.cast_for_compute(state.params), batch)

        return jax.jit(eval_fn)

    # ----------------------------------------------------------- collectives
    def gather(self, tree: Any) -> Any:
        return _ops.gather(tree)

    def reduce(self, tree: Any, reduction: str = "mean") -> Any:
        return _ops.reduce(tree, reduction)

    def pad_across_processes(self, tree: Any, dim: int = 0, pad_index: int = 0, pad_first: bool = False) -> Any:
        return _ops.pad_across_processes(tree, dim=dim, pad_index=pad_index, pad_first=pad_first)

    def gather_for_metrics(self, tree: Any, use_gather_object: bool = False) -> Any:
        """Gather eval outputs, dropping the samples duplicated by the
        even-batches wraparound on the last batch (reference
        `gather_for_metrics`, `accelerator.py:2601-2672`)."""
        if use_gather_object:
            return _ops.gather_object(list(tree))
        data = self.gather(tree)
        try:
            remainder = self.gradient_state.remainder
            on_last = self.gradient_state.end_of_dataloader
        except Exception:
            return data
        if on_last and remainder and remainder > 0:
            data = _ops.slice_tensors(data, slice(0, remainder))
        return data

    # -------------------------------------------------------------- tracking
    def init_trackers(
        self,
        project_name: str,
        config: dict | None = None,
        init_kwargs: dict | None = None,
    ) -> None:
        """Instantiate the trackers selected by ``log_with`` (reference
        `accelerator.py:2804`). ``init_kwargs`` is keyed by tracker name."""
        from . import tracking

        init_kwargs = init_kwargs or {}
        logging_dir = self.project_config.logging_dir
        self.trackers = []
        for entry in tracking.filter_trackers(self.log_with, logging_dir):
            if isinstance(entry, tracking.GeneralTracker):
                tracker = entry
            else:
                # Constructors have global side effects (run creation, open
                # files): instantiate on the main process only, unless the
                # tracker opts in to per-process runs (reference wandb
                # `main_process_only = False`, `tracking.py:289`).
                if entry.main_process_only and not self.is_main_process:
                    continue
                kwargs = dict(init_kwargs.get(entry.name, {}))
                if entry.requires_logging_directory:
                    kwargs.setdefault("logging_dir", logging_dir)
                tracker = entry(project_name, **kwargs)
            self.trackers.append(tracker)
        if config is not None:
            for tracker in self.trackers:
                tracker.store_init_configuration(config)

    def get_tracker(self, name: str, unwrap: bool = False) -> Any:
        """Fetch one initialized tracker by name (reference
        `accelerator.py:2850`); ``unwrap`` returns the raw library object.

        On non-main processes (where main-only trackers were never
        instantiated) a blank no-op tracker is returned, so user code can
        call this unguarded everywhere (reference :2878-2881)."""
        from . import tracking

        for tracker in self.trackers:
            if tracker.name == name:
                return tracker.tracker if unwrap else tracker
        if not self.is_main_process:
            return tracking.GeneralTracker(_blank=True)
        raise ValueError(
            f"Tracker {name!r} not found; initialized: "
            f"{[t.name for t in self.trackers]} (did you call init_trackers?)"
        )

    def log(
        self,
        values: dict,
        step: int | None = None,
        log_kwargs: dict | None = None,
    ) -> None:
        """Log metrics to every tracker (reference `accelerator.py:2883`).

        Device arrays (e.g. the metrics dict a compiled train step returned)
        are synced to host scalars HERE, once, so trackers never touch jax.
        """
        if not self.trackers:
            # No device->host sync when nothing consumes the metrics — the
            # fetch would serialize dispatch on TPU.
            return
        log_kwargs = log_kwargs or {}
        host_values = {
            k: (float(v) if hasattr(v, "dtype") and getattr(v, "ndim", 1) == 0 else v)
            for k, v in values.items()
        }
        if step is not None and hasattr(step, "item"):
            step = int(step)
        for tracker in self.trackers:
            tracker.log(host_values, step=step, **log_kwargs.get(tracker.name, {}))

    def end_training(self) -> None:
        """Flush/close all trackers (reference `accelerator.py:2912`), join
        any in-flight async checkpoint writer, and stand down the hang
        watchdog (its heartbeat expects a steady stream of steps; post-
        training eval/export must not trip it)."""
        for tracker in self.trackers:
            tracker.finish()
        self.trackers = []
        from . import checkpointing, resilience, telemetry

        # Final telemetry snapshot so the shared metrics dir reflects the
        # run's last state even when the step cadence never hit the flush.
        metrics_dir = os.environ.get("ATX_METRICS_DIR", "")
        if metrics_dir and telemetry.metrics_enabled():
            try:
                telemetry.write_snapshot(
                    metrics_dir, process_index=self.process_index
                )
            except OSError:
                pass

        wd = resilience.watchdog_from_env()
        if wd is not None:
            wd.stop()
        if self._health is not None:
            self._health.stop()
        checkpointing.wait_for_checkpoint()
        self._ship_collective_log()
        if self._replicator is not None:
            # The final checkpoint just landed in the queue (async saves
            # joined above): give its upload the drain window, then stop.
            from .resilience import replicate as _replicate

            if not self._replicator.stop(_replicate.drain_secs_from_env()):
                _replicate.logger.warning(
                    "checkpoint replication queue did not drain before "
                    "end_training returned; the last checkpoint may not be "
                    "durable remotely (raise ATX_REPLICATE_DRAIN_SECS)"
                )

    # -------------------------------------------------------------- triggers
    def set_trigger(self) -> None:
        """Cooperative cross-process abort flag (reference
        `accelerator.py:2391-2448`), used for early stopping."""
        self._flag_tensor = jnp.ones((), jnp.int32)

    def check_trigger(self) -> bool:
        flag = self._flag_tensor if self._flag_tensor is not None else jnp.zeros((), jnp.int32)
        total = _ops.reduce({"flag": np.asarray(flag)}, "sum")["flag"]
        if int(total) > 0:
            self._flag_tensor = None
            return True
        return False

    # ---------------------------------------------------------------- memory
    def free_memory(self, *objects: Any) -> tuple:
        """Release references + device buffers (reference `free_memory`,
        `accelerator.py:3412`)."""
        self._train_steps.clear()
        objects = tuple(None for _ in objects)
        gc.collect()
        jax.clear_caches()
        return objects

    # ------------------------------------------------------------ resilience
    def preemption_requested(self) -> bool:
        """Has a SIGTERM / maintenance notice arrived? (The handler only
        sets a flag; poll this at step boundaries and checkpoint + exit with
        ``resilience.PREEMPTION_EXIT_CODE`` — or rely on the automatic hook
        in the step helper when ``automatic_checkpoint_naming`` is on.)"""
        from . import resilience

        return resilience.preemption_requested()

    def _preemption_agreed(self) -> bool:
        """Cross-process agreement on the preemption flag (the orbax-style
        multihost preemption sync). SIGTERM delivery and Python signal
        dispatch skew across hosts: acting on the LOCAL flag alone lets one
        process enter the collective emergency save while peers are still
        issuing training-step collectives (mismatched collectives → hang
        until the watchdog/KILL, emergency checkpoint lost), or lets
        processes enter one step apart and commit shards mixing step N and
        N+1. Every process or-reduces its flag at the same step entries, so
        all agree on the exit step before any of them starts the save.

        ``ATX_PREEMPTION_SYNC_STEPS=N`` (default 1) syncs every N entries —
        raising it trades up to N-1 steps of notice-to-checkpoint latency
        for fewer per-step host round-trips."""
        from . import resilience

        if self.num_processes == 1:
            return resilience.preemption_requested()
        from .utils.environment import get_int_from_env

        self._preemption_sync_calls += 1
        interval = max(1, get_int_from_env(("ATX_PREEMPTION_SYNC_STEPS",), 1))
        if self._preemption_sync_calls % interval:
            return False
        local = resilience.preemption_requested()
        total = _ops.reduce({"flag": np.asarray(int(local), np.int32)}, "sum")["flag"]
        if int(total) == 0:
            return False
        if not local:
            # Adopt the peers' notice so local polls (`preemption_requested`)
            # and the second-SIGTERM escalation see consistent state.
            resilience.request_preemption()
        return True

    def _maybe_emergency_exit(self, state: "TrainState") -> None:
        """The step helper's automatic preemption hook: once ALL processes
        agree a preemption notice is pending (`_preemption_agreed` — the
        collective runs at every step entry so the whole group exits at the
        same step), write a committed emergency checkpoint and raise
        ``SystemExit(PREEMPTION_EXIT_CODE)`` — the exit code the elastic
        loop in `commands/launch.py` resumes immediately without burning a
        ``--max_restarts`` attempt. The save only fires under
        ``automatic_checkpoint_naming`` (otherwise there is no agreed place
        to save; the loop polls `preemption_requested` itself — by the time
        the agreement collective returns True, the flag is set on every
        process, so such loops also act at one common step boundary)."""
        from . import resilience

        if not self._preemption_agreed():
            return
        if not self.project_config.automatic_checkpoint_naming:
            return
        if self._preemption_exit_started:  # re-entry (e.g. user caught it)
            from .telemetry import flight as _flight

            _flight.dump_postmortem("preemption_exit_75_reentry")
            raise SystemExit(resilience.PREEMPTION_EXIT_CODE)
        self._preemption_exit_started = True
        # The emergency save may legitimately exceed the per-step deadline;
        # the watchdog must not shoot it down mid-commit.
        wd = resilience.watchdog_from_env()
        if wd is not None:
            wd.stop()
        import sys as _sys

        _sys.stderr.write(
            "[accelerate_tpu] preemption requested: writing emergency "
            "checkpoint before exiting\n"
        )
        from . import checkpointing

        path = checkpointing.save_state(self, None, state, async_save=False)
        if self._replicator is not None:
            # The emergency checkpoint is only preemption-proof once it is
            # durable OFF this VM: flush the upload queue, bounded by
            # ATX_REPLICATE_DRAIN_SECS so a dead store cannot eat the whole
            # grace window (a SIGKILL mid-drain still leaves the local
            # commit + any fully-uploaded parts for the next attempt).
            from .resilience import replicate as _replicate

            drain_secs = _replicate.drain_secs_from_env()
            _sys.stderr.write(
                "[accelerate_tpu] flushing checkpoint replication queue "
                f"(up to {drain_secs:.0f}s) before preemption exit\n"
            )
            if not self._replicator.stop(drain_secs):
                _sys.stderr.write(
                    "[accelerate_tpu] replication queue did not drain in "
                    "time; the emergency checkpoint may not be durable "
                    "remotely (already-uploaded parts will be skipped on "
                    "the next attempt)\n"
                )
        # Post-mortem shipping: the collective log (when armed) rides out on
        # the same store before the VM disappears. Best-effort by design.
        self._ship_collective_log()
        _sys.stderr.write(
            f"[accelerate_tpu] emergency checkpoint committed at {path}; "
            f"exiting with code {resilience.PREEMPTION_EXIT_CODE} (elastic "
            "launchers resume without consuming a restart attempt)\n"
        )
        _sys.stderr.flush()
        # Black-box bundle (no-op unless ATX_POSTMORTEM_DIR): what the
        # process was doing when the preemption notice landed. After the
        # checkpoint commit, so a slow collector can't eat grace time.
        from .telemetry import flight as _flight

        _flight.dump_postmortem(
            "preemption_exit_75", extra={"checkpoint": str(path)}
        )
        raise SystemExit(resilience.PREEMPTION_EXIT_CODE)

    def _ship_collective_log(self) -> None:
        """Ship this process's collective log off-host (best effort).

        Fires only when ``ATX_COLLECTIVE_LOG=1`` recorded a log AND a
        replicate store is armed — the log is a post-mortem aid, so failures
        here must never mask the exit path that called us."""
        try:
            from .analysis import collective_log as _cl

            if not _cl.enabled():
                return
            store = self._replicator.store if self._replicator is not None else None
            if store is None:
                from .resilience import replicate as _replicate

                store = _replicate.store_from_env()
            if store is None:
                return
            _cl.ship_log(store, process_index=self.process_index)
        except Exception as e:
            import logging

            logging.getLogger(__name__).warning(
                "collective-log shipping failed (post-mortem aid only): %s", e
            )

    # ---------------------------------------------------- elastic shrink/grow
    def on_topology_change(
        self, callback: Callable[[dict, dict, Any], None]
    ) -> Callable:
        """Register ``callback(old_signature, new_signature, decision)`` to
        fire after an in-place shrink/grow (signatures from
        `parallel.mesh.topology_signature`). The hook is where user code
        re-prepares anything pinned to the old world — dataloader sharding,
        logging of the new topology, LR rescaling for the changed global
        batch. Exceptions are logged, never raised (the resize already
        committed). Returns the callback (usable as a decorator)."""
        self._topology_callbacks.append(callback)
        return callback

    def _maybe_elastic_resize(
        self, state: "TrainState", step_hint: int
    ) -> "TrainState | None":
        """Step-entry elastic poll: the resized TrainState when the group
        just shrank/grew in place, None otherwise. Every failure mode —
        agreement timeout/conflict, unsupported layout, reshard holes —
        degrades to the existing emergency-save + exit-75 relaunch path by
        setting the preemption flag and letting `_maybe_emergency_exit`
        (the very next check in `run_step`) take over."""
        import sys as _sys

        from . import resilience
        from .resilience import elastic as _elastic

        try:
            decision = self._elastic.check(int(step_hint))
        except _elastic.AgreementError as e:
            _sys.stderr.write(
                f"[atx elastic] topology agreement failed ({e}); falling "
                "back to emergency-save + relaunch\n"
            )
            _sys.stderr.flush()
            resilience.request_preemption()
            return None
        if decision is None:
            return None
        try:
            return self._apply_topology_decision(state, decision)
        except Exception as e:
            _sys.stderr.write(
                f"[atx elastic] in-place resize failed before completion "
                f"({type(e).__name__}: {e}); falling back to emergency-save "
                "+ relaunch\n"
            )
            _sys.stderr.flush()
            self._elastic.abandon()
            resilience.request_preemption()
            return None

    def _apply_topology_decision(
        self, state: "TrainState", decision: Any
    ) -> "TrainState":
        """Execute an agreed resize: snapshot live shards, rebuild the
        distributed runtime + mesh at the new size, reshard
        params/opt-state/step in memory, and swing the health/elastic
        rosters over. Raises on any problem BEFORE mutating accelerator
        state wherever possible (the `shrink.before_reshard` fault point
        marks that boundary); the caller maps failures to the relaunch
        path."""
        import sys as _sys
        import time as _time

        from . import checkpointing as _ckpt
        from . import resilience
        from .resilience.commit import fault_point

        esc_at = self._elastic.escalated_at
        t0 = _time.monotonic()
        if esc_at is None:
            esc_at = t0
        old_sig = topology_signature(self.mesh)
        old_devices = self.mesh.devices.size
        if getattr(self, "_opt_host_shardings", None) is not None:
            raise RuntimeError(
                "host-offloaded optimizer state cannot be resized in place "
                "yet (its pinned-host shardings are tied to the old mesh)"
            )
        fault_point("shrink.before_reshard")
        # 1. Snapshot every live leaf to host — ALL addressable shards, so
        #    replica copies cover slices whose replica-0 owner died. This is
        #    the last read of the old-mesh arrays.
        template: dict[str, Any] = {
            "step": state.step,
            "params": state.params,
            "opt_state": state.opt_state,
        }
        if state.loss_scale is not None:
            template["loss_scale"] = state.loss_scale
        snapshot = _ckpt.InMemoryShardSource.from_tree(template)
        live_step = int(jax.device_get(state.step))
        # 2. Real multi-host worlds re-initialize the distributed runtime at
        #    the reduced size (survivor ranks densify via decision.rank_of).
        #    Single-process simulated worlds skip this — the mesh rebuild
        #    below is the whole transition.
        if (
            self.process_state.num_processes > 1
            and decision.num_processes != self.process_state.num_processes
        ):
            new_rank = decision.rank_of(self.process_state.process_index)
            if new_rank is None:
                raise RuntimeError(
                    f"rank {self.process_state.process_index} is not in the "
                    f"agreed survivor set {decision.survivors}"
                )
            import os as _os

            from .state import maybe_initialize_jax_distributed

            self.process_state.destroy_process_group()
            _os.environ["ATX_NUM_PROCESSES"] = str(decision.num_processes)
            _os.environ["ATX_PROCESS_ID"] = str(new_rank)
            maybe_initialize_jax_distributed()
        # 3. Rebuild the mesh with the same parallelism layout at the new
        #    device count; per-leaf partition specs must come out unchanged
        #    (a layout flip would need a different jit program — relaunch).
        want = decision.num_devices
        devs = list(jax.devices())
        if len(devs) < want:
            raise RuntimeError(
                f"resize wants {want} devices but only {len(devs)} are "
                "visible"
            )
        cfg = resize_mesh_config(self.mesh, want, devices=devs[:want])
        new_mesh = build_mesh(cfg)
        old_param_specs = self._param_specs
        self.state.set_mesh(new_mesh)
        try:
            params_shapes = jax.eval_shape(lambda p: p, state.params)
            self._resolve_specs(params_shapes, state.tx)
            if old_param_specs is not None and not _specs_equal(
                old_param_specs, self._param_specs
            ):
                raise RuntimeError(
                    "parameter partition specs differ at the new world size "
                    "(a leaf stopped dividing evenly); in-place resize would "
                    "silently change layouts"
                )
            shardings = self.state_shardings(state)
            shard_tree: dict[str, Any] = {
                "step": shardings.step,
                "params": shardings.params,
                "opt_state": shardings.opt_state,
            }
            if state.loss_scale is not None:
                shard_tree["loss_scale"] = shardings.loss_scale
            # 4. In-memory reshard: live local shards first; the replicate
            #    store's newest SAME-STEP committed checkpoint only for
            #    slices nobody alive holds (ranged reads, not whole files).
            try:
                restored = _ckpt.reshard_arrays(template, shard_tree, [snapshot])
            except _ckpt.CheckpointShardCoverageError:
                store = (
                    self._replicator.store if self._replicator is not None else None
                )
                if store is None:
                    from .resilience import replicate as _replicate

                    store = _replicate.store_from_env()
                fallback = (
                    _ckpt.store_fallback_source(store, live_step)
                    if store is not None
                    else None
                )
                if fallback is None:
                    raise
                _sys.stderr.write(
                    "[atx elastic] live shards have holes; streaming missing "
                    f"slices from remote {fallback.name} (byte-range reads)\n"
                )
                restored = _ckpt.reshard_arrays(
                    template, shard_tree, [snapshot, fallback]
                )
        except BaseException:
            # The mesh swing is the one mutation before this point; undo it
            # so the relaunch fallback saves the emergency checkpoint under
            # the topology the live arrays actually have. Best-effort: in a
            # torn-down real multi-host world this can itself fail, and the
            # relaunch path recovers regardless.
            try:
                if len(devs) >= old_devices:
                    self.state.set_mesh(
                        build_mesh(
                            resize_mesh_config(
                                new_mesh, old_devices, devices=devs[:old_devices]
                            )
                        )
                    )
                    if old_param_specs is not None:
                        params_shapes = jax.eval_shape(lambda p: p, state.params)
                        self._resolve_specs(params_shapes, state.tx)
            except Exception:
                pass
            raise
        new_state = state.replace(
            step=restored["step"],
            params=restored["params"],
            opt_state=restored["opt_state"],
            loss_scale=restored.get("loss_scale", state.loss_scale),
        )
        # 5. Roster swing: the health monitor stops scanning (and retires
        #    the beats of) departed ranks; the controller arms the next
        #    epoch. A health-escalated preemption flag is now satisfied —
        #    clear it so the emergency-exit path doesn't fire.
        if self._health is not None:
            self._health.adopt_roster(decision.survivors)
        self._elastic.adopt(decision)
        resilience.clear_preemption()
        self._mesh_epoch += 1
        new_sig = topology_signature(new_mesh)
        for cb in self._topology_callbacks:
            try:
                cb(old_sig, new_sig, decision)
            except Exception as e:
                _sys.stderr.write(
                    f"[atx elastic] on_topology_change callback failed: {e}\n"
                )
        kind = "grow" if decision.num_devices > old_devices else "shrink"
        agree_secs = (self._elastic.last_transition or {}).get("agree_secs", 0.0)
        reshard_secs = _time.monotonic() - t0
        if self._elastic.last_transition is not None:
            self._elastic.last_transition["reshard_secs"] = reshard_secs
        _sys.stderr.write(
            f"[atx elastic] {kind} in place (epoch {decision.epoch}): "
            f"{old_sig['num_devices']} -> {decision.num_devices} devices, "
            f"{decision.num_processes} process(es) x "
            f"{decision.host_devices} device(s) at step {live_step}; "
            f"agreement {agree_secs:.3f}s, reshard {reshard_secs:.3f}s\n"
        )
        _sys.stderr.flush()
        self._elastic_timer = (decision.epoch, kind, esc_at)
        return new_state

    def _report_elastic_latency(self, new_state: "TrainState") -> None:
        """Log escalation -> first post-resize step wall clock (the ISSUE's
        reported metric) after blocking once on that step's output."""
        import sys as _sys
        import time as _time

        epoch, kind, esc_at = self._elastic_timer
        self._elastic_timer = None
        try:
            jax.block_until_ready(new_state.step)
        except Exception:  # pragma: no cover - reporting must not kill steps
            pass
        _sys.stderr.write(
            f"[atx elastic] epoch {epoch} {kind}: escalation -> first "
            f"post-{kind} step {_time.monotonic() - esc_at:.3f}s\n"
        )
        _sys.stderr.flush()

    # ------------------------------------------------------------ checkpoint
    def register_for_checkpointing(self, *objects: Any) -> None:
        """Attach arbitrary stateful objects (must expose state_dict /
        load_state_dict) to save_state/load_state (reference
        `accelerator.py:3550`)."""
        for obj in objects:
            if not (hasattr(obj, "state_dict") and hasattr(obj, "load_state_dict")):
                raise ValueError(
                    f"Object {obj!r} must define state_dict() and load_state_dict() "
                    "to be registered for checkpointing"
                )
            self._checkpoint_registry.append(obj)

    def save_state(self, output_dir: str, state: TrainState, **kwargs: Any) -> str:
        from . import checkpointing

        return checkpointing.save_state(self, output_dir, state, **kwargs)

    def load_state(
        self, input_dir: str | None, state: TrainState, **kwargs: Any
    ) -> TrainState:
        """Restore a checkpoint. ``load_state(None, state, resume="latest")``
        discovers the newest *committed* checkpoint under the automatic-
        naming root, verifies its manifest, and falls back to the previous
        committed one on corruption (docs/fault_tolerance.md)."""
        from . import checkpointing

        return checkpointing.load_state(self, input_dir, state, **kwargs)

    def save_model(self, params: Any, output_dir: str, **kwargs: Any) -> str:
        """Params-only inference checkpoint (reference `save_model`,
        `accelerator.py:3020`). Layout follows the FSDP plugin's
        ``state_dict_type``: FULL_STATE_DICT consolidates to one file,
        SHARDED_STATE_DICT keeps per-process shards."""
        from . import checkpointing

        kwargs.setdefault(
            "consolidate", self.strategy.fsdp.state_dict_type == "FULL_STATE_DICT"
        )
        return checkpointing.save_model(self, params, output_dir, **kwargs)

    # -------------------------------------------------------------- profiling
    def profile(self, profile_kwargs: Any = None):
        """Capture a `jax.profiler` trace of the enclosed block (reference
        `accelerator.profile()`, `accelerator.py:3614`). Trace files land in
        ``profile_kwargs.output_trace_dir`` or ``<logging_dir>/atx_profile``;
        open the directory with TensorBoard to see the device timeline.

        Run warmup steps before entering — compilation inside the context
        dominates the timeline otherwise.
        """
        from .utils import profiler as _profiler

        return _profiler.profile(
            profile_kwargs, logging_dir=self.project_config.logging_dir
        )

    # ---------------------------------------------------------------- misc
    def autocast(self):
        """Apply the dtype policy to ad-hoc computations OUTSIDE the compiled
        train/eval steps (reference `autocast`, `accelerator.py:3587`).

        JAX has no global op interception, so the context (a) activates the
        fp8 matmul mode when the policy is fp8 — any `matmul_einsum` traced
        inside lowers to scaled-fp8 contractions, exactly as in the compiled
        steps — and (b) yields the policy's cast function for the operands::

            with accelerator.autocast() as cast:
                out = model_fn(cast(params), batch)

        fp8 pitfall: the matmul mode is read at *trace* time and is not part
        of jit's cache key. A function you ``jax.jit`` yourself and first
        call inside this context bakes fp8 contractions into its cached
        executable (and keeps them outside the context); traced first
        outside, it never gets fp8. Either trace the function fresh per mode
        (e.g. pass a ``static_argnum`` flag derived from the policy) or keep
        fp8 work inside the Accelerator's own compiled steps, which close
        over the mode correctly.
        """
        import contextlib

        @contextlib.contextmanager
        def ctx():
            with _fp8.fp8_matmuls(self.policy.fp8):
                yield self.policy.cast_for_compute

        return ctx()

    def __repr__(self) -> str:
        return (
            f"Accelerator(mesh={dict(self.mesh.shape)}, "
            f"strategy={self.strategy.kind}, precision={self.mixed_precision!r}, "
            f"accum={self.gradient_accumulation_steps})"
        )

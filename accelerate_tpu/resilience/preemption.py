"""SIGTERM / maintenance-notice preemption handling.

TPU preemptions (spot reclaim, maintenance events) deliver SIGTERM with a
short grace window. The handler here does the *minimum* a signal handler
safely can — set a flag — and the training loop turns the flag into an
emergency checkpoint at the next step boundary:

- ``Accelerator.make_train_step``'s returned step checks the flag at entry
  (before any compute, so every completed step's metrics were already
  returned) and, when ``automatic_checkpoint_naming`` gives it a place to
  save, writes a committed emergency checkpoint and raises
  ``SystemExit(PREEMPTION_EXIT_CODE)``;
- loops without automatic naming poll ``accelerator.preemption_requested()``
  themselves and save wherever they choose.

``PREEMPTION_EXIT_CODE`` (75, BSD ``EX_TEMPFAIL``) is the exit-code
contract with the elastic loop in ``commands/launch.py``: a worker group
that dies with it is resumed immediately WITHOUT burning a
``--max_restarts`` attempt — the checkpoint is known-good, so the restart
is not a failure.

A second SIGTERM while the flag is already set forces the DEFAULT
disposition and re-delivers the signal, so an impatient supervisor (or the
launcher's own group teardown) can still terminate a process that never
reaches a step boundary. Explicitly ``SIG_DFL`` — not the pre-install
disposition: a process that started with SIGTERM ignored (``SIG_IGN``)
would otherwise re-deliver the second TERM into an ignoring handler and
never die.
"""

from __future__ import annotations

import os
import signal
import sys
import threading
from typing import Iterable

PREEMPTION_EXIT_CODE = 75  # EX_TEMPFAIL: transient failure, retry == resume

_flag = threading.Event()
_installed_signals: dict[int, object] = {}


def install_preemption_handler(
    signals: Iterable[int] = (signal.SIGTERM,),
) -> bool:
    """Install the flag-setting handler for ``signals`` (idempotent).

    Returns False (and installs nothing) off the main thread or when the
    interpreter refuses (e.g. an embedded runtime) — signal handlers can
    only be registered from the main thread. ``Accelerator.__init__`` calls
    this automatically unless ``ATX_PREEMPTION_HANDLER=0``.
    """
    if threading.current_thread() is not threading.main_thread():
        return False
    try:
        for sig in signals:
            if sig in _installed_signals:
                continue
            _installed_signals[sig] = signal.signal(sig, _handler)
    except (ValueError, OSError):  # pragma: no cover - non-main interpreter
        return False
    return True


def _handler(signum: int, frame) -> None:
    if _flag.is_set():
        # Second notice: the escalation path. Force the DEFAULT disposition
        # and re-deliver so the process actually dies (the launcher's
        # teardown, or a supervisor that ran out of patience). Never restore
        # the pre-install disposition here: SIG_IGN (truthy) would swallow
        # the re-delivery and the process would only die at the launcher's
        # SIGKILL escalation — or hang forever under supervisors without one.
        signal.signal(signum, signal.SIG_DFL)
        os.kill(os.getpid(), signum)
        return
    _flag.set()
    sys.stderr.write(
        f"[accelerate_tpu] received signal {signum}: preemption requested — "
        "an emergency checkpoint will be written at the next step boundary "
        f"(exit code {PREEMPTION_EXIT_CODE})\n"
    )
    sys.stderr.flush()
    prev = _installed_signals.get(signum)
    if callable(prev) and prev is not _handler:
        prev(signum, frame)  # chain a user handler we displaced


def preemption_requested() -> bool:
    """Has a preemption notice (SIGTERM / `request_preemption`) arrived?"""
    return _flag.is_set()


def request_preemption() -> None:
    """Set the preemption flag programmatically — for maintenance-notice
    pollers (e.g. a thread watching the GCE metadata server) and tests."""
    _flag.set()


def clear_preemption() -> None:
    """Reset the flag (tests / a loop that chose to keep training)."""
    _flag.clear()


def _reset_for_tests() -> None:
    """Restore the original signal dispositions and clear all state."""
    for sig, prev in list(_installed_signals.items()):
        try:
            signal.signal(sig, prev if prev is not None else signal.SIG_DFL)
        except (ValueError, OSError):  # pragma: no cover
            pass
    _installed_signals.clear()
    _flag.clear()

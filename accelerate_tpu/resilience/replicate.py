"""Durable checkpoint replication to object storage.

A committed local checkpoint (`resilience/commit.py`) survives a kill -9 —
but not the loss of the node it lives on: a preempted TPU VM takes its
local disk with it. The :class:`Replicator` closes that gap by mirroring
every committed checkpoint into an :class:`ObjectStore` in the background:

- **Resumable, part-based uploads.** Each manifest-listed file is one
  *part*, content-addressed by the SHA-256 the PR-4 manifests already
  record. Before uploading a part the remote object is stat'ed; a part
  whose remote size (and hash, when the store can report one) matches the
  manifest is skipped — so a replication attempt killed mid-upload resumes
  where it left off instead of re-shipping gigabytes.
- **Remote COMMIT marker last.** The remote directory follows the exact
  local commit protocol: data parts, then the per-process manifests, then
  ``MANIFEST.agg.json``, then the ``COMMIT`` marker — a remote checkpoint
  is *durable* if and only if its marker exists, and a crash at any upload
  instant leaves debris the restore path ignores.
- **Bounded retry with jittered exponential backoff** on transport errors
  (``ATX_REPLICATE_RETRIES``, per-checkpoint deadline
  ``ATX_REPLICATE_TIMEOUT_SECS``), plus an optional bandwidth throttle
  (``ATX_REPLICATE_BANDWIDTH_MIB_S``) so replication never starves the
  training job's network.
- **Graceful degradation.** Replication runs on a daemon worker thread and
  issues NO collectives; a permanently failing store logs a warning and
  training continues — durability is best-effort, the step loop is not.

Restore: `restore_latest` walks remote *committed* checkpoints newest
first, downloads into a local ``.tmp`` dir, republishes it with the local
commit protocol (marker written last), and `verify_checkpoint`s the result
— `checkpointing.load_state(resume="latest")` falls back to it when the
local checkpoint root is empty or entirely corrupt.

Like `resilience/commit.py`, this module is dependency-free (no jax) so
the launcher and tests can import it cheaply. Enable with
``ATX_REPLICATE_URL=<store url>`` (``file:///path`` or a plain path for
the filesystem store; other schemes via `register_store_scheme`); force
off with ``ATX_REPLICATE=0``.
"""

from __future__ import annotations

import json
import logging
import os
import queue
import random
import re
import shutil
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable

from ..utils.environment import get_int_from_env, parse_flag_from_env
from . import commit as _commit
from .commit import fault_point

logger = logging.getLogger(__name__)

REPLICATE_URL_ENV = "ATX_REPLICATE_URL"
REPLICATE_ENV = "ATX_REPLICATE"


class ObjectStoreError(RuntimeError):
    """A store operation failed (transport errors raise subclasses or any
    exception the backing client uses — the Replicator retries them all)."""


class TransientStoreError(ObjectStoreError):
    """A retryable transport failure (timeouts, 5xx, connection resets)."""


@dataclass
class ObjectStat:
    """Metadata for a stored object. ``sha256`` is None when the store
    cannot report a content hash cheaply (the skip check then falls back to
    size-only and the final verify_checkpoint still catches corruption)."""

    size: int
    sha256: str | None = None


class ObjectStore:
    """Minimal object-store interface the Replicator uploads through.

    Contract: ``put_file``/``put_bytes`` must be **atomic** — a reader may
    observe the object fully written or not at all, never a partial body
    (every real object store and the tmp+rename filesystem implementation
    below satisfy this). Keys are ``/``-separated paths; there are no
    directories, only prefixes.
    """

    def put_file(self, local_path: str, key: str) -> None:
        raise NotImplementedError

    def put_bytes(self, data: bytes, key: str) -> None:
        raise NotImplementedError

    def get_file(self, key: str, local_path: str) -> None:
        raise NotImplementedError

    def get_bytes(self, key: str) -> bytes:
        raise NotImplementedError

    def get_range(self, key: str, start: int, length: int) -> bytes:
        """``length`` bytes of the object starting at byte ``start``.

        Reads past the end of the object return the available suffix (like
        a file read), so callers can over-ask for zip tails. The base
        implementation downloads the whole object and slices — correct for
        any store; Local/GCS override with true ranged reads so the elastic
        reshard path fetches only the byte ranges a leaf needs.
        """
        if start < 0 or length < 0:
            raise ValueError(f"invalid range start={start} length={length}")
        return self.get_bytes(key)[start : start + length]

    def copy(self, src_key: str, dst_key: str) -> None:
        """Copy one object to a new key inside the store. The base
        implementation round-trips through the client (get + put — correct
        for any store); LocalObjectStore overrides with a server-side file
        copy, and real object stores should use their native server-side
        copy so differential replication never re-sends unchanged bytes."""
        self.put_bytes(self.get_bytes(src_key), dst_key)

    def exists(self, key: str) -> bool:
        return self.stat(key) is not None

    def stat(self, key: str) -> ObjectStat | None:
        raise NotImplementedError

    def list(self, prefix: str = "") -> list[str]:
        """All keys under ``prefix`` (recursive), sorted."""
        raise NotImplementedError

    def delete(self, key: str) -> None:
        raise NotImplementedError

    def delete_prefix(self, prefix: str) -> int:
        n = 0
        for key in self.list(prefix):
            self.delete(key)
            n += 1
        return n


class LocalObjectStore(ObjectStore):
    """Filesystem-backed store (tests, CI, and NFS/FUSE-mounted buckets).

    Writes are atomic (tempfile + ``os.replace``), `stat` reports a real
    SHA-256 (files are hashed on demand), so the resumable-upload skip
    check is exact here."""

    def __init__(self, root: str) -> None:
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)

    def _path(self, key: str) -> str:
        path = os.path.normpath(os.path.join(self.root, key))
        if not path.startswith(self.root + os.sep) and path != self.root:
            raise ObjectStoreError(f"key {key!r} escapes store root {self.root!r}")
        return path

    def put_file(self, local_path: str, key: str) -> None:
        dst = self._path(key)
        os.makedirs(os.path.dirname(dst), exist_ok=True)
        tmp = dst + f".put.{os.getpid()}"
        shutil.copyfile(local_path, tmp)
        os.replace(tmp, dst)

    def put_bytes(self, data: bytes, key: str) -> None:
        dst = self._path(key)
        os.makedirs(os.path.dirname(dst), exist_ok=True)
        tmp = dst + f".put.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, dst)

    def get_file(self, key: str, local_path: str) -> None:
        src = self._path(key)
        if not os.path.isfile(src):
            raise ObjectStoreError(f"no object {key!r} in {self.root}")
        os.makedirs(os.path.dirname(os.path.abspath(local_path)), exist_ok=True)
        shutil.copyfile(src, local_path)

    def get_bytes(self, key: str) -> bytes:
        src = self._path(key)
        if not os.path.isfile(src):
            raise ObjectStoreError(f"no object {key!r} in {self.root}")
        with open(src, "rb") as f:
            return f.read()

    def get_range(self, key: str, start: int, length: int) -> bytes:
        if start < 0 or length < 0:
            raise ValueError(f"invalid range start={start} length={length}")
        src = self._path(key)
        if not os.path.isfile(src):
            raise ObjectStoreError(f"no object {key!r} in {self.root}")
        with open(src, "rb") as f:
            f.seek(start)
            return f.read(length)

    def stat(self, key: str) -> ObjectStat | None:
        path = self._path(key)
        if not os.path.isfile(path):
            return None
        return ObjectStat(
            size=os.path.getsize(path), sha256=_commit.file_sha256(path)
        )

    def list(self, prefix: str = "") -> list[str]:
        out: list[str] = []
        for dirpath, _, filenames in os.walk(self.root):
            for name in filenames:
                if name.endswith(f".put.{os.getpid()}"):
                    continue  # in-flight atomic writes are not objects yet
                rel = os.path.relpath(os.path.join(dirpath, name), self.root)
                key = rel.replace(os.sep, "/")
                if key.startswith(prefix):
                    out.append(key)
        return sorted(out)

    def copy(self, src_key: str, dst_key: str) -> None:
        src = self._path(src_key)
        if not os.path.isfile(src):
            raise ObjectStoreError(f"no object {src_key!r} in {self.root}")
        dst = self._path(dst_key)
        os.makedirs(os.path.dirname(dst), exist_ok=True)
        tmp = dst + f".put.{os.getpid()}"
        shutil.copyfile(src, tmp)
        os.replace(tmp, dst)

    def delete(self, key: str) -> None:
        try:
            os.remove(self._path(key))
        except FileNotFoundError:
            pass

    def __repr__(self) -> str:
        return f"LocalObjectStore({self.root!r})"


# ------------------------------------------------------------ scheme registry
_SCHEME_REGISTRY: dict[str, Callable[[str], ObjectStore]] = {}


def register_store_scheme(scheme: str, factory: Callable[[str], ObjectStore]) -> None:
    """Register ``factory(url) -> ObjectStore`` for ``<scheme>://`` URLs —
    how a deployment plugs in GCS/S3/etc. without this package depending on
    any cloud SDK."""
    _SCHEME_REGISTRY[scheme.lower()] = factory


def store_for_url(url: str) -> ObjectStore:
    """Resolve a store URL. ``file:///path`` and bare paths map to
    `LocalObjectStore`; other schemes must have been registered via
    `register_store_scheme` (``gs://`` ships a stub that explains how)."""
    m = re.match(r"^([a-zA-Z][a-zA-Z0-9+.-]*)://(.*)$", url)
    if not m:
        return LocalObjectStore(url)
    scheme, rest = m.group(1).lower(), m.group(2)
    factory = _SCHEME_REGISTRY.get(scheme)
    if factory is None:
        raise ObjectStoreError(
            f"no ObjectStore registered for scheme {scheme!r} (url {url!r}); "
            "call resilience.replicate.register_store_scheme("
            f"{scheme!r}, factory) first — known schemes: "
            f"{sorted(_SCHEME_REGISTRY)}"
        )
    return factory(url if scheme not in ("file",) else rest)


def _file_store(path: str) -> ObjectStore:
    # file://HOST/path has an empty host for local URLs: file:///a/b -> /a/b
    return LocalObjectStore("/" + path.lstrip("/") if path.startswith("/") else path)


def _gcs_store(url: str) -> ObjectStore:
    # Lazy import: gcs.py itself gates on google-cloud-storage availability
    # and raises a clear ObjectStoreError (install the SDK, or gcsfuse-mount
    # the bucket and use the filesystem store) when the SDK is missing.
    from .gcs import GcsObjectStore

    return GcsObjectStore.from_url(url)


register_store_scheme("file", _file_store)
register_store_scheme("gs", _gcs_store)


# ----------------------------------------------------------------- replicator
@dataclass
class _Job:
    directory: str
    process_index: int
    num_processes: int
    each_node: bool
    total_limit: int | None


def _env_float(key: str, default: float) -> float:
    try:
        return float(os.environ.get(key, "") or default)
    except ValueError:
        return default


class Replicator:
    """Background uploader: `enqueue` committed checkpoint directories, a
    daemon worker mirrors them into ``store`` with the remote commit
    protocol. Failure NEVER propagates to the caller — a checkpoint that
    could not be replicated is logged (`failures` counter) and training
    continues; the next enqueue retries nothing retroactively (the next
    checkpoint supersedes it anyway).
    """

    def __init__(
        self,
        store: ObjectStore,
        *,
        retries: int | None = None,
        timeout_secs: float | None = None,
        bandwidth_mib_s: float | None = None,
    ) -> None:
        self.store = store
        self.retries = (
            retries
            if retries is not None
            else get_int_from_env(("ATX_REPLICATE_RETRIES",), 5)
        )
        self.timeout_secs = (
            timeout_secs
            if timeout_secs is not None
            else _env_float("ATX_REPLICATE_TIMEOUT_SECS", 600.0)
        )
        self.bandwidth_mib_s = (
            bandwidth_mib_s
            if bandwidth_mib_s is not None
            else _env_float("ATX_REPLICATE_BANDWIDTH_MIB_S", 0.0)
        )
        self._queue: "queue.Queue[_Job]" = queue.Queue()
        self._thread: threading.Thread | None = None
        self._idle = threading.Event()
        self._idle.set()
        self._stopped = False
        self._lock = threading.Lock()
        # Observability counters (read by tests and the drain log line).
        self.parts_uploaded = 0
        self.parts_skipped = 0
        self.parts_unchanged = 0
        self.checkpoints_replicated = 0
        self.failures = 0
        self.last_error: str | None = None
        # Registry mirrors of the attributes above — same names prefixed
        # replicate_* on /metrics (docs/observability.md). The attributes
        # stay the source of truth for tests/log lines; the counters are
        # the fleet-visible copy.
        from .. import telemetry as _telemetry

        self._c_parts_uploaded = _telemetry.counter(
            "replicate_parts_uploaded", "Checkpoint parts uploaded to the object store")
        self._c_parts_skipped = _telemetry.counter(
            "replicate_parts_skipped", "Checkpoint parts skipped (already durable)")
        self._c_parts_unchanged = _telemetry.counter(
            "replicate_parts_unchanged",
            "Checkpoint parts satisfied by server-side copy from the "
            "previous remote checkpoint (SHA-256 unchanged)")
        self._c_checkpoints = _telemetry.counter(
            "replicate_checkpoints", "Checkpoint directories fully replicated")
        self._c_failures = _telemetry.counter(
            "replicate_failures", "Checkpoint replications abandoned after retries")

    # ------------------------------------------------------------- lifecycle
    def enqueue(
        self,
        directory: str,
        *,
        process_index: int = 0,
        num_processes: int = 1,
        each_node: bool = False,
        total_limit: int | None = None,
    ) -> None:
        """Queue a *committed* checkpoint directory for upload. Called by
        the committing process right after local rotation; cheap (no IO)."""
        if self._stopped:
            return
        self._idle.clear()
        self._queue.put(
            _Job(directory, process_index, num_processes, each_node, total_limit)
        )
        self._ensure_thread()

    def drain(self, timeout_secs: float) -> bool:
        """Block until every queued upload finished (or failed), up to the
        deadline. Returns True when the queue fully drained — the
        emergency-save flush before a preemption exit."""
        deadline = time.monotonic() + max(0.0, timeout_secs)
        while time.monotonic() < deadline:
            if self._idle.is_set() and self._queue.empty():
                return True
            time.sleep(0.05)
        return self._idle.is_set() and self._queue.empty()

    def stop(self, drain_secs: float = 0.0) -> bool:
        """Stop accepting work; optionally drain first. Returns the drain
        verdict (True when nothing was pending)."""
        drained = self.drain(drain_secs) if drain_secs > 0 else (
            self._idle.is_set() and self._queue.empty()
        )
        self._stopped = True
        return drained

    def _ensure_thread(self) -> None:
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._run, name="atx-replicator", daemon=True
                )
                self._thread.start()

    def _run(self) -> None:
        while True:
            try:
                job = self._queue.get(timeout=0.2)
            except queue.Empty:
                self._idle.set()
                continue
            try:
                self._replicate(job)
                self.checkpoints_replicated += 1
                self._c_checkpoints.inc()
            except BaseException as e:  # NEVER crash the step loop
                self.failures += 1
                self._c_failures.inc()
                self.last_error = f"{type(e).__name__}: {e}"
                logger.warning(
                    "checkpoint replication of %s failed (%s) — training "
                    "continues; this checkpoint is NOT durable in %r",
                    job.directory,
                    self.last_error,
                    self.store,
                )
            finally:
                self._queue.task_done()
                if self._queue.empty():
                    self._idle.set()

    # ----------------------------------------------------------------- upload
    def _remote_prefix(self, job: _Job) -> str:
        # save_on_each_node commits one directory per process; namespace the
        # remote copies per node so they never collide.
        name = os.path.basename(os.path.abspath(job.directory))
        if job.each_node and job.num_processes > 1:
            return f"node_{job.process_index}/{name}"
        return name

    def _replicate(self, job: _Job) -> None:
        directory = job.directory
        if not _commit.is_committed(directory):
            raise ObjectStoreError(
                f"{directory} is not a committed checkpoint (no "
                f"{_commit.COMMIT_MARKER} marker) — refusing to replicate"
            )
        deadline = time.monotonic() + self.timeout_secs
        prefix = self._remote_prefix(job)
        if self.store.exists(f"{prefix}/{_commit.COMMIT_MARKER}"):
            # Already durable (a backfill re-enqueue after resume, or a
            # duplicate notify): nothing to do — remote commits are final.
            return
        t0 = time.monotonic()
        uploaded0, skipped0 = self.parts_uploaded, self.parts_skipped
        # 1. data parts: every manifest-listed file, content-addressed by
        #    the manifest's SHA-256 (skip parts already durable remotely).
        manifests = sorted(
            n
            for n in os.listdir(directory)
            if _commit._MANIFEST_PATTERN.match(n)
        )
        if not manifests:
            raise ObjectStoreError(
                f"{directory} has no manifests; pre-manifest legacy "
                "checkpoints are not replicated"
            )
        # Differential replication: shards whose SHA-256 already exists in
        # the previous remote checkpoint's aggregate manifest are satisfied
        # by a server-side copy instead of a re-upload (frozen params, EMA
        # shadows, and data-loader state are often byte-identical between
        # consecutive checkpoints).
        prev_index = self._previous_manifest_index(job, prefix)
        for mname in manifests:
            with open(os.path.join(directory, mname)) as f:
                manifest = json.load(f)
            for rel, info in manifest["files"].items():
                self._upload_part(
                    directory, prefix, rel, info, deadline, prev_index=prev_index
                )
        # 2. the manifests themselves, then the aggregate — a restore needs
        #    them to verify, so they precede the marker.
        for mname in manifests:
            self._upload_part(directory, prefix, mname, None, deadline)
        if os.path.exists(os.path.join(directory, _commit.AGG_MANIFEST)):
            self._upload_part(directory, prefix, _commit.AGG_MANIFEST, None, deadline)
        # 3. remote COMMIT marker LAST: the remote durability point.
        fault_point("replicate.before_marker")
        marker = os.path.join(directory, _commit.COMMIT_MARKER)
        self._with_retries(
            f"{prefix}/{_commit.COMMIT_MARKER}",
            lambda: self.store.put_file(marker, f"{prefix}/{_commit.COMMIT_MARKER}"),
            deadline,
        )
        logger.info(
            "replicated %s -> %r (%d parts uploaded, %d already durable, "
            "%.1fs)",
            directory,
            self.store,
            self.parts_uploaded - uploaded0,
            self.parts_skipped - skipped0,
            time.monotonic() - t0,
        )
        # 4. remote rotation mirrors the local total_limit — only AFTER the
        #    new remote commit landed, and never the checkpoint just written.
        if job.total_limit is not None:
            self._rotate_remote(job, prefix)

    def _previous_manifest_index(self, job: _Job, current_prefix: str) -> dict[str, str]:
        """``{sha256: remote_key}`` over every file of the NEWEST previous
        committed remote checkpoint, parsed from its aggregate manifest.
        Any failure (no previous checkpoint, missing/corrupt aggregate,
        store error) degrades to an empty index — differential copy is an
        optimization, never a correctness dependency."""
        try:
            root = (
                f"node_{job.process_index}/"
                if (job.each_node and job.num_processes > 1)
                else ""
            )
            committed = remote_committed_checkpoints(self.store, node_prefix=root)
            prev = next(
                (p for _, p in reversed(committed) if p != current_prefix), None
            )
            if prev is None:
                return {}
            agg = json.loads(
                self.store.get_bytes(f"{prev}/{_commit.AGG_MANIFEST}").decode("utf-8")
            )
            index: dict[str, str] = {}
            for proc in agg.get("processes", {}).values():
                for rel, info in proc.get("files", {}).items():
                    index[info["sha256"]] = f"{prev}/{rel.replace(os.sep, '/')}"
            return index
        except Exception:
            return {}

    def _upload_part(
        self,
        directory: str,
        prefix: str,
        rel: str,
        info: dict[str, Any] | None,
        deadline: float,
        *,
        prev_index: dict[str, str] | None = None,
    ) -> None:
        local = os.path.join(directory, rel)
        key = f"{prefix}/{rel.replace(os.sep, '/')}"
        if info is not None:
            remote = self._with_retries(key, lambda: self.store.stat(key), deadline)
            if (
                remote is not None
                and remote.size == info["size"]
                and (remote.sha256 is None or remote.sha256 == info["sha256"])
            ):
                self.parts_skipped += 1
                self._c_parts_skipped.inc()
                return
            src = (prev_index or {}).get(info["sha256"])
            if src is not None and src != key:
                # Single attempt, no retries: a failed copy costs one round
                # trip and the part simply uploads the normal way.
                try:
                    self.store.copy(src, key)
                except Exception:
                    pass
                else:
                    self.parts_unchanged += 1
                    self._c_parts_unchanged.inc()
                    fault_point("replicate.part_uploaded")
                    return
        self._throttle(os.path.getsize(local))
        self._with_retries(key, lambda: self.store.put_file(local, key), deadline)
        self.parts_uploaded += 1
        self._c_parts_uploaded.inc()
        fault_point("replicate.part_uploaded")

    def _throttle(self, nbytes: int) -> None:
        """Pace uploads to ATX_REPLICATE_BANDWIDTH_MIB_S by sleeping the
        difference between real elapsed time and the budgeted transfer
        time — a token-bucket without burst credit, so a background
        replication cannot saturate the NIC the training collectives use."""
        if self.bandwidth_mib_s <= 0:
            return
        budget = nbytes / (self.bandwidth_mib_s * (1 << 20))
        now = time.monotonic()
        ready_at = max(getattr(self, "_next_send_at", now), now)
        self._next_send_at = ready_at + budget
        wait = ready_at - now
        if wait > 0:
            time.sleep(wait)

    def _with_retries(self, desc: str, fn: Callable[[], Any], deadline: float) -> Any:
        """Bounded exponential backoff + full jitter (the coordinator-init
        policy from `state.py`): 0.5s -> 1s -> 2s ... capped at 30s, each
        multiplied by 1+U(0,1); gives up on the retry budget OR the
        per-checkpoint deadline, whichever comes first."""
        delay = 0.5
        failures = 0
        while True:
            try:
                return fn()
            except Exception as e:
                failures += 1
                if failures > self.retries or time.monotonic() >= deadline:
                    raise
                sleep_for = min(delay * (1.0 + random.random()), 30.0)
                logger.warning(
                    "transient store error on %s (attempt %d/%d): %s — "
                    "retrying in %.1fs",
                    desc,
                    failures,
                    self.retries,
                    e,
                    sleep_for,
                )
                self._sleep(sleep_for)
                delay = min(delay * 2.0, 30.0)

    def _sleep(self, secs: float) -> None:  # test seam
        time.sleep(secs)

    # --------------------------------------------------------------- rotation
    def _rotate_remote(self, job: _Job, current_prefix: str) -> None:
        root = f"node_{job.process_index}/" if (job.each_node and job.num_processes > 1) else ""
        committed = remote_committed_checkpoints(self.store, node_prefix=root)
        keep = max(0, len(committed) - int(job.total_limit))
        for n, prefix in committed[:keep]:
            if prefix == current_prefix:
                continue
            try:
                self.store.delete_prefix(prefix + "/")
            except Exception as e:  # rotation is best-effort housekeeping
                logger.warning("remote rotation of %s failed: %s", prefix, e)


# ------------------------------------------------------------------- restore
def remote_committed_checkpoints(
    store: ObjectStore, *, node_prefix: str = ""
) -> list[tuple[int, str]]:
    """``(iteration, remote_prefix)`` for every remote checkpoint whose
    ``COMMIT`` marker exists, sorted oldest -> newest — the remote analog of
    `commit.committed_checkpoints` (uncommitted upload debris is invisible
    by construction)."""
    out: list[tuple[int, str]] = []
    for key in store.list(node_prefix):
        rel = key[len(node_prefix):]
        m = re.match(r"^checkpoint_(\d+)/" + re.escape(_commit.COMMIT_MARKER) + "$", rel)
        if m:
            out.append((int(m.group(1)), node_prefix + f"checkpoint_{m.group(1)}"))
    return sorted(out)


def restore_latest(
    store: ObjectStore,
    local_root: str,
    *,
    process_index: int = 0,
    num_processes: int = 1,
    each_node: bool = False,
) -> str | None:
    """Download the newest remote *committed* checkpoint into
    ``local_root`` and republish it under the local commit protocol.

    Walks remote committed checkpoints newest first; each candidate is
    downloaded into ``<final>.tmp`` (invisible to resume), renamed, its
    ``COMMIT`` marker written LAST (so a crash mid-download leaves only
    debris the next save's rotation reclaims), then `verify_checkpoint`'d —
    a candidate whose downloaded bytes fail verification is deleted and the
    next older one is tried. Returns the committed local path, or None when
    the store holds nothing usable. No collectives: multi-host callers
    coordinate by letting process 0 download onto the shared filesystem
    while peers poll for the committed directory to appear.
    """
    node_prefix = f"node_{process_index}/" if (each_node and num_processes > 1) else ""
    candidates = remote_committed_checkpoints(store, node_prefix=node_prefix)
    for n, prefix in reversed(candidates):
        final = os.path.join(local_root, f"checkpoint_{n}")
        if _commit.is_committed(final) and not _commit.verify_checkpoint(final):
            return final  # already present AND intact locally
        # absent — or committed locally but corrupt: re-download over it
        tmp = final + _commit.TMP_SUFFIX
        shutil.rmtree(tmp, ignore_errors=True)
        shutil.rmtree(final, ignore_errors=True)
        os.makedirs(tmp, exist_ok=True)
        try:
            keys = store.list(prefix + "/")
            marker_key = f"{prefix}/{_commit.COMMIT_MARKER}"
            for key in keys:
                rel = key[len(prefix) + 1 :]
                if key == marker_key:
                    continue
                store.get_file(key, os.path.join(tmp, rel.replace("/", os.sep)))
            marker_bytes = store.get_bytes(marker_key)
        except Exception as e:
            logger.warning(
                "download of remote checkpoint %s failed: %s — trying the "
                "previous one",
                prefix,
                e,
            )
            shutil.rmtree(tmp, ignore_errors=True)
            continue
        os.rename(tmp, final)
        # Local COMMIT written last, atomically — same ordering as commit_dir.
        marker_path = os.path.join(final, _commit.COMMIT_MARKER)
        mtmp = marker_path + ".tmp"
        with open(mtmp, "wb") as f:
            f.write(marker_bytes)
            f.flush()
            os.fsync(f.fileno())
        os.replace(mtmp, marker_path)
        _commit._fsync_dir(final)
        errors = _commit.verify_checkpoint(final)
        if errors:
            logger.warning(
                "remote checkpoint %s failed verification after download "
                "(%s) — trying the previous one",
                prefix,
                "; ".join(errors[:3]),
            )
            shutil.rmtree(final, ignore_errors=True)
            continue
        logger.info("restored %s from %r -> %s", prefix, store, final)
        return final
    return None


# ------------------------------------------------------------------ from env
def replication_enabled() -> bool:
    """Replication is ON iff a store URL is configured and ``ATX_REPLICATE``
    is not explicitly 0 — default-off without a URL, default-on with one."""
    if not os.environ.get(REPLICATE_URL_ENV):
        return False
    return parse_flag_from_env(REPLICATE_ENV, True)


def store_from_env() -> ObjectStore | None:
    if not replication_enabled():
        return None
    return store_for_url(os.environ[REPLICATE_URL_ENV])


def replicator_from_env() -> Replicator | None:
    """The Replicator configured by ``ATX_REPLICATE_URL`` (None when
    replication is off). Called from ``Accelerator.__init__``; a bad URL or
    unregistered scheme warns and disables rather than failing training."""
    if not replication_enabled():
        return None
    try:
        store = store_for_url(os.environ[REPLICATE_URL_ENV])
    except Exception as e:
        logger.warning(
            "ATX_REPLICATE_URL=%r is unusable (%s) — checkpoint replication "
            "disabled",
            os.environ.get(REPLICATE_URL_ENV),
            e,
        )
        return None
    return Replicator(store)


def drain_secs_from_env() -> float:
    """How long a preemption exit / end_training waits for pending uploads
    (``ATX_REPLICATE_DRAIN_SECS``, default 120s — inside the typical
    preemption grace window, after the emergency save itself)."""
    return _env_float("ATX_REPLICATE_DRAIN_SECS", 120.0)

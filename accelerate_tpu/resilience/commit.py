"""Atomic checkpoint commit protocol.

A checkpoint is *committed* when — and only when — its directory contains a
``COMMIT`` marker. The writer's contract (`checkpointing.save_state`):

1. every file is written into a sibling ``<final>.tmp/`` directory, never
   into the final path;
2. each process writes a ``manifest_<proc>.json`` of SHA-256 + size for the
   files it wrote, AFTER all of them are on disk;
3. a multi-host barrier (collective on the sync path, ``.precommit_<proc>``
   marker files on the async path — a background thread must not run
   collectives the main thread may also be issuing);
4. process 0 renames ``<final>.tmp`` → ``<final>`` and writes the ``COMMIT``
   marker last (tempfile + ``os.replace`` + fsync of file and parent dir);
5. rotation (``total_limit``) deletes old checkpoints only AFTER the new
   commit lands.

A crash at ANY instant therefore leaves either (a) a stale ``.tmp`` dir, or
(b) a renamed dir with no ``COMMIT`` — both invisible to
``load_state(resume="latest")``, which only considers committed directories
and verifies their manifests before trusting a byte (falling back to the
previous committed checkpoint on corruption).

This module is dependency-free (no jax) so the launcher and tests can import
it cheaply.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import shutil
import time
from typing import Any

COMMIT_MARKER = "COMMIT"
TMP_SUFFIX = ".tmp"
MANIFEST_FILE = "manifest_{proc}.json"
AGG_MANIFEST = "MANIFEST.agg.json"
PRECOMMIT_FILE = ".precommit_{proc}"
_MANIFEST_PATTERN = re.compile(r"^manifest_(\d+)\.json$")
_CKPT_PATTERN = re.compile(r"^checkpoint_(\d+)$")


class CheckpointIntegrityWarning(UserWarning):
    """A committed checkpoint failed manifest verification and was skipped
    (resume fell back to the previous committed checkpoint)."""


class CheckpointShardCoverageError(ValueError):
    """An elastic (topology-changed) restore could not assemble some leaf's
    GLOBAL value: the shard files reachable from this process (local dir +
    fetched peer shards + remote store) leave a hole in the array. Raised
    instead of silently resuming on a partial reshard; ``resume="latest"``
    catches it, warns, and falls back to the previous committed checkpoint."""


def _maybe_collective_log(kind: str, name: str) -> None:
    """Opt-in runtime mirror of the ATX5xx collective log
    (``ATX_COLLECTIVE_LOG=1``): the commit barrier halves are part of the
    cross-process schedule, so multi-process tests can assert every process
    agreed on save ordering. The lazy import only happens when the flag is
    set, preserving this module's cheap-import contract by default."""
    if os.environ.get("ATX_COLLECTIVE_LOG", "").strip().lower() not in (
        "1",
        "true",
        "yes",
        "on",
    ):
        return
    try:
        from ..analysis.collective_log import runtime_record

        runtime_record(kind, name)
    except Exception:  # pragma: no cover - diagnostics must not break saves
        pass


def fault_point(name: str) -> None:
    """Fault-injection hook. No-op (one dict lookup) unless the test harness
    set ``ATX_FAULT_KILL_AT`` (simulated kill -9 via ``os._exit``),
    ``ATX_FAULT_RAISE_AT`` (in-process `FaultInjected`), or
    ``ATX_FAULT_HANG_AT`` (park the thread — the wedge analog), or
    ``ATX_FAULT_DELAY_AT`` (inject ``ATX_FAULT_DELAY_SECS`` of latency —
    the slow-transport analog) — see `test_utils/faults.py` for the
    instrumented points and the ``point@N`` fire-on-Nth-hit syntax."""
    if (
        "ATX_FAULT_KILL_AT" in os.environ
        or "ATX_FAULT_RAISE_AT" in os.environ
        or "ATX_FAULT_HANG_AT" in os.environ
        or "ATX_FAULT_DELAY_AT" in os.environ
    ):
        from ..test_utils.faults import crash_point

        crash_point(name)


# ------------------------------------------------------------------ manifests
def file_sha256(path: str, chunk_bytes: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            block = f.read(chunk_bytes)
            if not block:
                break
            h.update(block)
    return h.hexdigest()


def write_manifest(
    directory: str, proc: int, files: list[str], *, step: int | None = None
) -> str:
    """Hash ``files`` (paths relative to ``directory``) into
    ``manifest_<proc>.json``. Called after every listed file is fully
    written; the manifest itself is replaced atomically so a crash mid-write
    can never leave a parseable-but-partial manifest.

    ``step`` records the training step THIS process wrote, so
    `verify_checkpoint` can reject a checkpoint whose shards mix steps —
    processes that entered save_state one step apart (preemption-notice
    skew on a pod) would otherwise commit a consistent-looking directory
    that resumes on inconsistent state."""
    entries: dict[str, Any] = {}
    for rel in files:
        path = os.path.join(directory, rel)
        entries[rel] = {"sha256": file_sha256(path), "size": os.path.getsize(path)}
    payload: dict[str, Any] = {"version": 1, "process": proc, "files": entries}
    if step is not None:
        payload["step"] = int(step)
    out = os.path.join(directory, MANIFEST_FILE.format(proc=proc))
    tmp = out + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, out)
    return out


def write_aggregate_manifest(directory: str) -> str | None:
    """Collapse every ``manifest_<proc>.json`` in ``directory`` into one
    ``MANIFEST.agg.json``.

    Written by process 0 AFTER the commit barrier (every peer's manifest is
    visible then) and BEFORE ``commit_dir``, so the aggregate rides inside
    the committed directory. It exists for filesystems that are per-node
    rather than shared: a replica downloaded onto (or verified on) a node
    that never held peers' ``manifest_<proc>.json`` files can still answer
    "which processes wrote this checkpoint, with which files, at which
    step" — `verify_checkpoint` falls back to it for any process whose
    per-proc manifest is absent. Returns the path, or None when there are
    no manifests to aggregate (pre-manifest legacy directories)."""
    processes: dict[str, Any] = {}
    for mpath in _manifest_paths(directory):
        proc = _MANIFEST_PATTERN.match(os.path.basename(mpath)).group(1)
        with open(mpath) as f:
            manifest = json.load(f)
        entry: dict[str, Any] = {"files": manifest["files"]}
        if manifest.get("step") is not None:
            entry["step"] = int(manifest["step"])
        processes[proc] = entry
    if not processes:
        return None
    out = os.path.join(directory, AGG_MANIFEST)
    tmp = out + ".tmp"
    with open(tmp, "w") as f:
        json.dump(
            {"version": 1, "num_processes": len(processes), "processes": processes},
            f,
        )
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, out)
    return out


def _read_aggregate(directory: str) -> dict[int, dict[str, Any]]:
    """``{proc: {"files": ..., "step": ...}}`` from ``MANIFEST.agg.json``,
    or ``{}`` when absent. Raises ValueError on a present-but-unparseable
    aggregate (corruption, not legacy)."""
    path = os.path.join(directory, AGG_MANIFEST)
    if not os.path.exists(path):
        return {}
    with open(path) as f:
        payload = json.load(f)
    return {int(proc): entry for proc, entry in payload["processes"].items()}


def _manifest_paths(directory: str) -> list[str]:
    if not os.path.isdir(directory):
        return []
    return sorted(
        os.path.join(directory, name)
        for name in os.listdir(directory)
        if _MANIFEST_PATTERN.match(name)
    )


def verify_checkpoint(directory: str) -> list[str]:
    """Check every manifest-listed file's existence, size, and SHA-256 —
    plus two cross-process invariants on committed checkpoints:

    - **completeness**: the ``COMMIT`` marker records how many processes
      wrote the checkpoint; losing an entire process's files (manifest +
      shards deleted together) must not verify clean, or resume="latest"
      would pick the amputated checkpoint over the previous good one.
      (``save_on_each_node`` directories are per-node by design — one
      manifest each — and are exempt.)
    - **step agreement**: every manifest (and the marker) must record the
      same training step; shards mixing step N and N+1 would pass per-file
      hashing but resume on inconsistent state.

    Returns a list of human-readable errors (empty = verified). A directory
    with no manifest and no ``COMMIT`` marker is treated as a pre-manifest
    legacy checkpoint and passes vacuously; a *committed* directory with no
    manifest is an error (the protocol writes manifests before the marker).

    **Aggregate fallback** (per-node filesystems): a process whose
    ``manifest_<proc>.json`` is absent but which appears in
    ``MANIFEST.agg.json`` is verified from the aggregate instead. If NONE
    of that process's files exist locally the checkpoint is per-node (the
    peer's shards live on its own disk) and the process passes; if SOME
    exist, the partial set is corruption and every absent file is an error.
    Completeness counts aggregate-covered processes as writers, so losing a
    peer's manifest no longer amputates the checkpoint — while legacy
    directories (no aggregate) verify exactly as before.
    """
    if not os.path.isdir(directory):
        return [f"{directory} is not a directory"]
    marker: dict[str, Any] = {}
    if is_committed(directory):
        try:
            marker = read_commit_marker(directory)
        except (ValueError, OSError) as e:
            return [f"unreadable {COMMIT_MARKER} marker: {e}"]
    manifests = _manifest_paths(directory)
    try:
        aggregate = _read_aggregate(directory)
    except (ValueError, KeyError, OSError) as e:
        return [f"unreadable {AGG_MANIFEST}: {e}"]
    if not manifests and not aggregate:
        if is_committed(directory):
            return [f"committed checkpoint {directory} has no manifest files"]
        return []
    errors: list[str] = []
    on_disk_procs = {
        int(_MANIFEST_PATTERN.match(os.path.basename(p)).group(1))
        for p in manifests
    }
    covered_procs = on_disk_procs | set(aggregate)
    recorded_procs = marker.get("num_processes")
    if recorded_procs is not None and not marker.get("save_on_each_node"):
        if len(covered_procs) != int(recorded_procs):
            errors.append(
                f"manifest count mismatch: {len(covered_procs)} writer "
                f"process(es) covered by manifests on disk + {AGG_MANIFEST} "
                f"but the {COMMIT_MARKER} marker records "
                f"{recorded_procs} writer process(es)"
            )
    steps: dict[int, list[str]] = {}

    def _check_entries(entries: dict[str, Any], *, require_all: bool) -> None:
        present = [rel for rel in entries if os.path.exists(os.path.join(directory, rel))]
        if not require_all and not present:
            return  # per-node checkpoint: this process's files live elsewhere
        for rel, info in entries.items():
            path = os.path.join(directory, rel)
            if not os.path.exists(path):
                errors.append(f"missing file {rel}")
                continue
            size = os.path.getsize(path)
            if size != info["size"]:
                errors.append(
                    f"size mismatch for {rel}: {size} bytes on disk, "
                    f"{info['size']} in manifest"
                )
                continue
            if file_sha256(path) != info["sha256"]:
                errors.append(f"sha256 mismatch for {rel}")

    for mpath in manifests:
        try:
            with open(mpath) as f:
                manifest = json.load(f)
            entries = manifest["files"]
        except (ValueError, KeyError) as e:
            errors.append(f"unreadable manifest {os.path.basename(mpath)}: {e}")
            continue
        if manifest.get("step") is not None:
            steps.setdefault(int(manifest["step"]), []).append(
                os.path.basename(mpath)
            )
        _check_entries(entries, require_all=True)
    for proc in sorted(set(aggregate) - on_disk_procs):
        entry = aggregate[proc]
        if entry.get("step") is not None:
            steps.setdefault(int(entry["step"]), []).append(
                f"{AGG_MANIFEST}[{proc}]"
            )
        _check_entries(entry["files"], require_all=False)
    if len(steps) > 1:
        errors.append(
            "cross-process step mismatch: "
            + "; ".join(
                f"step {s} in {', '.join(names)}" for s, names in sorted(steps.items())
            )
        )
    marker_step = marker.get("step")
    if marker_step is not None and steps and set(steps) != {int(marker_step)}:
        errors.append(
            f"manifest step(s) {sorted(steps)} disagree with the "
            f"{COMMIT_MARKER} marker's step {marker_step}"
        )
    return errors


# ------------------------------------------------------------------- markers
def is_committed(directory: str) -> bool:
    return os.path.exists(os.path.join(directory, COMMIT_MARKER))


def read_commit_marker(directory: str) -> dict[str, Any]:
    with open(os.path.join(directory, COMMIT_MARKER)) as f:
        return json.load(f)


def _fsync_dir(path: str) -> None:
    # Directory fsync makes the rename/marker durable on POSIX; best-effort
    # (not every filesystem supports opening a directory).
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:  # pragma: no cover - exotic fs
        return
    try:
        os.fsync(fd)
    except OSError:  # pragma: no cover
        pass
    finally:
        os.close(fd)


def commit_dir(tmp_dir: str, final_dir: str, meta: dict[str, Any] | None = None) -> None:
    """Publish ``tmp_dir`` as the committed checkpoint ``final_dir``:
    rename, then write the ``COMMIT`` marker last.

    If ``final_dir`` already exists (an explicit-output-dir re-save), it is
    moved aside first and deleted after the new directory is committed —
    under ``automatic_checkpoint_naming`` (the crash-safe workflow) the
    final name is always fresh and this path never runs.
    """
    _maybe_collective_log("commit", "commit_dir")
    fault_point("commit.before_rename")
    aside = None
    if os.path.isdir(final_dir):
        aside = final_dir + ".replaced"
        shutil.rmtree(aside, ignore_errors=True)
        os.rename(final_dir, aside)
    os.rename(tmp_dir, final_dir)
    fault_point("commit.before_marker")
    marker = os.path.join(final_dir, COMMIT_MARKER)
    tmp = marker + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"version": 1, "committed_at": time.time(), **(meta or {})}, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, marker)
    _fsync_dir(final_dir)
    _fsync_dir(os.path.dirname(os.path.abspath(final_dir)))
    if aside is not None:
        shutil.rmtree(aside, ignore_errors=True)


# --------------------------------------------------------- async-path barrier
def mark_precommit(tmp_dir: str, proc: int) -> None:
    """File-based barrier half for the async-save path: each process drops a
    marker once its files + manifest are fully written."""
    _maybe_collective_log("precommit", "mark_precommit")
    path = os.path.join(tmp_dir, PRECOMMIT_FILE.format(proc=proc))
    with open(path, "w") as f:
        f.flush()
        os.fsync(f.fileno())


def wait_for_precommit(tmp_dir: str, num_processes: int, timeout_secs: float) -> None:
    """Process 0's half of the file barrier: poll until every process's
    marker exists (shared filesystem), then remove the markers so they never
    appear in the committed directory."""
    _maybe_collective_log("precommit_wait", "wait_for_precommit")
    deadline = time.monotonic() + timeout_secs
    paths = [
        os.path.join(tmp_dir, PRECOMMIT_FILE.format(proc=p))
        for p in range(num_processes)
    ]
    while True:
        missing = [p for p in paths if not os.path.exists(p)]
        if not missing:
            break
        if time.monotonic() > deadline:
            raise RuntimeError(
                f"async checkpoint commit timed out after {timeout_secs:.0f}s "
                f"waiting for {len(missing)} process(es) to finish writing "
                f"{tmp_dir} (first missing: {os.path.basename(missing[0])}); "
                "raise ATX_COMMIT_BARRIER_SECS if the write is legitimately "
                "slow"
            )
        time.sleep(0.05)
    for p in paths:
        try:
            os.remove(p)
        except FileNotFoundError:  # pragma: no cover - racing cleaner
            pass


# ----------------------------------------------------------------- discovery
def checkpoint_iteration(name: str) -> int | None:
    m = _CKPT_PATTERN.match(name)
    return int(m.group(1)) if m else None


def committed_checkpoints(root: str) -> list[tuple[int, str]]:
    """``(iteration, path)`` for every *committed* ``checkpoint_<n>`` under
    ``root``, sorted oldest → newest. Uncommitted dirs (crash debris) and
    ``.tmp`` dirs are excluded by construction."""
    if not os.path.isdir(root):
        return []
    out = []
    for name in os.listdir(root):
        n = checkpoint_iteration(name)
        if n is None:
            continue
        path = os.path.join(root, name)
        if os.path.isdir(path) and is_committed(path):
            out.append((n, path))
    return sorted(out)


def latest_committed(root: str) -> str | None:
    found = committed_checkpoints(root)
    return found[-1][1] if found else None


def remove_stale_tmp(root: str) -> list[str]:
    """Delete leftover ``checkpoint_*.tmp`` dirs (crashed saves). Safe to
    call only while no save is in flight — `save_state` runs it during
    post-commit rotation, which the async saver serializes."""
    removed = []
    if not os.path.isdir(root):
        return removed
    for name in os.listdir(root):
        if name.endswith(TMP_SUFFIX) and checkpoint_iteration(name[: -len(TMP_SUFFIX)]) is not None:
            path = os.path.join(root, name)
            if os.path.isdir(path):
                shutil.rmtree(path, ignore_errors=True)
                removed.append(path)
    return removed

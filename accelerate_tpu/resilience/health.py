"""Peer-health watchdog: collective-free heartbeats between pod processes.

A wedged or dead peer normally surfaces as a hung collective — every other
process parks inside the all-reduce until ``ATX_WATCHDOG_SECS`` (a per-step
deadline measured in minutes, since it must cover legitimate long steps)
finally fires. This module detects the sick peer *directly*, in seconds:

- every process heartbeats a small counter file/object (local checkpoint
  root or the ``ATX_REPLICATE_URL`` store — the same sentinel-polling style
  as the PR-9 remote restore, NO collectives) every ``ATX_HEALTH_BEAT_SECS``;
- a monitor thread on each process scans the peers' beats; a peer whose
  counter has not advanced for ``ATX_HEALTH_STALE_SECS`` is flagged — the
  straggler's last-known training step is logged — and the monitor
  escalates to the existing preemption path (`request_preemption`), so the
  next step boundary takes the emergency-save + exit-75 route and the
  launcher's elastic loop restarts the group at whatever size survives;
- if the group still hasn't exited ``ATX_HEALTH_EXIT_SECS`` later (the step
  boundary never came — the survivor itself is parked in a collective with
  the dead peer), the monitor hard-aborts with ``PREEMPTION_EXIT_CODE`` so
  the restart fires anyway.

Knobs (all read by `health_from_env`):

- ``ATX_HEALTH_BEAT_SECS``   — beat + scan period; unset/0 disables (default).
- ``ATX_HEALTH_STALE_SECS``  — silence before a peer is stale (default 5x beat).
- ``ATX_HEALTH_EXIT_SECS``   — grace between escalation and hard abort
  (default 4x stale; 0 disables the hard abort).
- ``ATX_HEALTH_DIR``         — beat directory override (else ``<checkpoint
  root>/.health`` or the replicate store under ``health/``).
- ``ATX_HEALTH_PEERS``       — expected process count override.

Like `commit`, this module is jax-free so it stays cheap to import and
trivially testable single-process (`PeerHealthMonitor.tick` is the whole
loop body, public for deterministic tests).
"""

from __future__ import annotations

import json
import logging
import os
import sys
import threading
import time
from typing import Any, Callable

from .preemption import PREEMPTION_EXIT_CODE, request_preemption

logger = logging.getLogger(__name__)

BEAT_FILE = "beat_{proc}.json"
STORE_PREFIX = "health/"


# ------------------------------------------------------------------ backends
class _FileBackend:
    """Beats as files in a shared directory (checkpoint root / ATX_HEALTH_DIR)."""

    def __init__(self, directory: str):
        self.directory = directory

    def write(self, proc: int, payload: dict[str, Any]) -> None:
        os.makedirs(self.directory, exist_ok=True)
        path = os.path.join(self.directory, BEAT_FILE.format(proc=proc))
        tmp = f"{path}.tmp.{proc}"
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, path)  # readers never see a partial beat

    def read(self, proc: int) -> dict[str, Any] | None:
        path = os.path.join(self.directory, BEAT_FILE.format(proc=proc))
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def delete(self, proc: int) -> None:
        try:
            os.remove(os.path.join(self.directory, BEAT_FILE.format(proc=proc)))
        except OSError:
            pass

    def __repr__(self) -> str:  # pragma: no cover - logging only
        return f"_FileBackend({self.directory!r})"


class _StoreBackend:
    """Beats as objects in the replicate store (per-node filesystems: the
    store is the only surface every process can both write and read)."""

    def __init__(self, store, prefix: str = STORE_PREFIX):
        self.store = store
        self.prefix = prefix

    def write(self, proc: int, payload: dict[str, Any]) -> None:
        self.store.put_bytes(
            json.dumps(payload).encode(),
            self.prefix + BEAT_FILE.format(proc=proc),
        )

    def read(self, proc: int) -> dict[str, Any] | None:
        try:
            raw = self.store.get_bytes(self.prefix + BEAT_FILE.format(proc=proc))
            return json.loads(raw.decode())
        except Exception:
            return None

    def delete(self, proc: int) -> None:
        try:
            self.store.delete(self.prefix + BEAT_FILE.format(proc=proc))
        except Exception:
            pass

    def __repr__(self) -> str:  # pragma: no cover - logging only
        return f"_StoreBackend({self.store!r})"


# ------------------------------------------------------------------- monitor
class PeerHealthMonitor:
    """One beat-writer + peer-scanner per process.

    A peer that has NEVER been seen is ignored (startup grace by
    construction: processes come up at different times, and a smaller
    restarted group simply never sees the dead ranks' beats). Once a peer's
    counter has been observed, silence beyond ``stale_secs`` flags it.
    """

    def __init__(
        self,
        process_index: int,
        num_processes: int,
        backend,
        *,
        beat_secs: float = 5.0,
        stale_secs: float | None = None,
        exit_after_secs: float | None = None,
        escalate: Callable[[], None] | None = None,
        abort: Callable[[int], None] | None = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.process_index = int(process_index)
        self.num_processes = int(num_processes)
        self.backend = backend
        self.beat_secs = float(beat_secs)
        self.stale_secs = float(
            stale_secs if stale_secs is not None else 5.0 * self.beat_secs
        )
        self.exit_after_secs = float(
            exit_after_secs if exit_after_secs is not None else 4.0 * self.stale_secs
        )
        self._escalate = escalate if escalate is not None else request_preemption
        self._abort = abort if abort is not None else self._default_abort
        self._clock = clock
        # Ranks to scan. Starts as range(num_processes); an elastic shrink
        # rewrites it via `adopt_roster` (survivor old-ranks are preserved,
        # so the roster can be non-contiguous after a mid-rank loss).
        self.roster: tuple[int, ...] = tuple(range(self.num_processes))
        self._roster_lock = threading.Lock()
        self._seq = 0
        self._step = 0
        # peer -> (last observed seq, clock() when it last advanced, last step)
        self._peer_state: dict[int, tuple[int, float, int]] = {}
        self.stale_peers: set[int] = set()
        self.escalations = 0
        self.aborts = 0
        self.beats_written = 0
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        # Registry mirrors (docs/observability.md): the attributes above
        # stay the test-facing source of truth; these feed /metrics.
        from .. import telemetry as _telemetry

        self._c_beats = _telemetry.counter(
            "health_beats_written", "Heartbeat files/objects written")
        self._c_escalations = _telemetry.counter(
            "health_escalations", "Stale-peer escalations to the preemption path")
        self._g_stale = _telemetry.gauge(
            "health_stale_peers", "Peers currently flagged stale", aggregate="max")

    @staticmethod
    def _default_abort(code: int) -> None:  # pragma: no cover - kills the proc
        sys.stderr.write(
            "[atx health] hard abort: stale peer(s) persisted past "
            "ATX_HEALTH_EXIT_SECS and the group never reached a step "
            f"boundary; exiting {code} for the elastic restart\n"
        )
        sys.stderr.flush()
        os._exit(code)

    # -- producer side -------------------------------------------------------
    def note_step(self, step: int) -> None:
        """Record the current training step (host int, no device sync); it
        rides in the beat payload so a flagged straggler's last-known step
        lands in the survivors' logs."""
        self._step = int(step)

    def _write_beat(self) -> None:
        self._seq += 1
        try:
            self.backend.write(
                self.process_index,
                {
                    "process": self.process_index,
                    "seq": self._seq,
                    "step": self._step,
                    "time": time.time(),
                },
            )
            self.beats_written += 1
            self._c_beats.inc()
        except Exception as e:  # diagnostics must never kill training
            logger.warning("[atx health] beat write failed: %s", e)

    # -- roster --------------------------------------------------------------
    def adopt_roster(
        self,
        roster,
        *,
        process_index: int | None = None,
        retire_beats: bool = True,
    ) -> None:
        """Adopt a new peer set after an elastic shrink/grow.

        ``roster`` is the surviving (old-)rank list. Departed ranks' tracked
        state and stale flags are dropped and their beat files/objects are
        deleted (best-effort, idempotent across survivors) — without this a
        shrunk group would flag the dead peer as stale forever via
        ``ATX_HEALTH_PEERS``/beat-dir scans. Re-added ranks start with the
        never-seen startup grace."""
        new = tuple(sorted(int(p) for p in roster))
        with self._roster_lock:
            departed = set(self.roster) - set(new)
            self.roster = new
            self.num_processes = len(new)
            if process_index is not None:
                self.process_index = int(process_index)
            for peer in departed:
                self._peer_state.pop(peer, None)
                self.stale_peers.discard(peer)
                if retire_beats:
                    try:
                        self.backend.delete(peer)
                    except Exception as e:  # pragma: no cover - best-effort
                        logger.warning(
                            "[atx health] beat retirement for peer %d "
                            "failed: %s",
                            peer,
                            e,
                        )
        if departed:
            logger.warning(
                "[atx health] roster adopted: %d peer(s) now %r (retired %r)",
                len(new),
                new,
                sorted(departed),
            )

    # -- monitor side --------------------------------------------------------
    def _scan_peers(self) -> None:
        now = self._clock()
        with self._roster_lock:
            roster = self.roster
        for peer in roster:
            if peer == self.process_index:
                continue
            payload = self.backend.read(peer)
            if payload is None:
                continue  # never seen / unreadable: startup grace
            try:
                seq = int(payload.get("seq", 0))
                step = int(payload.get("step", -1))
            except (TypeError, ValueError):
                continue
            prev = self._peer_state.get(peer)
            if prev is None or seq != prev[0]:
                self._peer_state[peer] = (seq, now, step)
                if peer in self.stale_peers:
                    self.stale_peers.discard(peer)
                    self._g_stale.set(len(self.stale_peers))
                    logger.warning(
                        "[atx health] peer %d recovered (beat advanced)", peer
                    )
                continue
            silent = now - prev[1]
            if silent <= self.stale_secs:
                continue
            if peer not in self.stale_peers:
                self.stale_peers.add(peer)
                logger.warning(
                    "[atx health] peer %d is stale: no heartbeat for %.1fs "
                    "(> ATX_HEALTH_STALE_SECS=%.1fs); last-known step %d. "
                    "Escalating to emergency-save + exit-%d so the elastic "
                    "launcher restarts the group.",
                    peer,
                    silent,
                    self.stale_secs,
                    prev[2],
                    PREEMPTION_EXIT_CODE,
                )
                self.escalations += 1
                self._c_escalations.inc()
                self._g_stale.set(len(self.stale_peers))
                try:
                    self._escalate()
                except Exception as e:  # pragma: no cover - diagnostics only
                    logger.warning("[atx health] escalation failed: %s", e)
            elif (
                self.exit_after_secs > 0
                and silent > self.stale_secs + self.exit_after_secs
            ):
                # The step boundary never came — we are probably parked in a
                # collective with the dead peer. Abort so the restart fires.
                self.aborts += 1
                self._abort(PREEMPTION_EXIT_CODE)

    def tick(self) -> None:
        """One beat + one peer scan — the entire loop body, public so tests
        drive the protocol deterministically (injected clock, no thread)."""
        self._write_beat()
        self._scan_peers()

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="atx-health", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.tick()
            except Exception as e:  # pragma: no cover - never kill training
                logger.warning("[atx health] tick failed: %s", e)
            self._stop.wait(self.beat_secs)

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=max(5.0, 2.0 * self.beat_secs))
            self._thread = None


# ----------------------------------------------------------------- env entry
def _env_float(name: str, default: float | None) -> float | None:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        return default


def health_from_env(
    *,
    root: str | None = None,
    store=None,
    process_index: int | None = None,
    num_processes: int | None = None,
) -> PeerHealthMonitor | None:
    """Build the monitor from the env contract; None unless
    ``ATX_HEALTH_BEAT_SECS`` is set > 0 (opt-in, like the step watchdog).

    Beat surface precedence: ``ATX_HEALTH_DIR`` > replicate ``store`` >
    ``<root>/.health``. With none of the three available the monitor is
    disabled with a warning rather than raising — health checking is an
    aid, not a correctness requirement.
    """
    beat = _env_float("ATX_HEALTH_BEAT_SECS", 0.0) or 0.0
    if beat <= 0:
        return None
    if process_index is None:
        process_index = int(os.environ.get("ATX_PROCESS_ID", "0") or 0)
    if num_processes is None:
        num_processes = int(os.environ.get("ATX_NUM_PROCESSES", "1") or 1)
    peers_override = os.environ.get("ATX_HEALTH_PEERS", "").strip()
    if peers_override:
        try:
            num_processes = int(peers_override)
        except ValueError:
            pass
    health_dir = os.environ.get("ATX_HEALTH_DIR", "").strip()
    if health_dir:
        backend = _FileBackend(health_dir)
    elif store is not None:
        backend = _StoreBackend(store)
    elif root:
        backend = _FileBackend(os.path.join(root, ".health"))
    else:
        logger.warning(
            "[atx health] ATX_HEALTH_BEAT_SECS set but no beat surface "
            "(no ATX_HEALTH_DIR, no replicate store, no checkpoint root); "
            "peer-health monitoring disabled"
        )
        return None
    stale = _env_float("ATX_HEALTH_STALE_SECS", None)
    exit_after = _env_float("ATX_HEALTH_EXIT_SECS", None)
    return PeerHealthMonitor(
        process_index,
        num_processes,
        backend,
        beat_secs=beat,
        stale_secs=stale,
        exit_after_secs=exit_after,
    )

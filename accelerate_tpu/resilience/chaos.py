"""Seeded chaos campaigns over the serving fleet and replication path.

`atx chaos` drives N *episodes*; each episode derives a deterministic
sub-seed, samples a `test_utils.faults.FaultSchedule` over one
subsystem's registered crash points (`faults.active_points`), runs a
small seeded workload under that fault env, and asserts the invariants
that hold the whole stack together:

- **exactly-once**: every admitted request resolves exactly once, and a
  stream callback delivers each token once across failover replays;
- **bit-identity**: greedy outputs match a solo engine token-for-token
  (references computed OUTSIDE the fault env, memoized across episodes);
- **drain**: the preemption flag flips the router to draining on the
  next tick and admissions are refused (the exit-75 contract; the
  subprocess episode checks the literal exit code);
- **no lost committed checkpoint**: a replication fault never yields a
  torn remote commit, and a clean retry converges to a restorable one.

Violations are *collected*, not raised, so a campaign always completes
and reports: one JSON line per episode (schedule, violations, detail)
plus a summary carrying a SHA-256 digest over all sampled schedules —
two runs with the same ``--seed`` produce the same digest, which is the
replay contract (re-run a failing seed, get the same fault assignment).

Episode subsystems rotate through ``kinds``: ``router`` (raise/delay at
``router.replica<i>.step`` — quarantine, probation re-admission, prefix
migration), ``engine`` (raise/delay at ``engine.step``), ``replication``
(raise/delay at ``replicate.*`` with a differential second checkpoint).
``subprocess_episodes=True`` appends the two out-of-process episodes:
kill -9 (exit 137) mid-replication followed by a clean converge, and a
SIGTERM drain of a threaded router that must exit 75. The subprocess
workers live in this module's ``__main__``.

Everything serving-related is imported lazily inside functions:
``serving.engine`` imports the ``resilience`` package for its fault
hooks, so a module-level import here would be circular.
"""

from __future__ import annotations

import hashlib
import json
import os
import random
import signal
import subprocess
import sys
import tempfile
import time
from typing import Sequence

import numpy as np

from ..telemetry import flight as _flight
from ..test_utils import faults
from ..utils.environment import patch_environment
from . import commit as _commit
from . import preemption as _preemption
from . import replicate as _replicate

__all__ = ["run_campaign", "EPISODE_KINDS"]

EPISODE_KINDS = ("router", "engine", "replication")

_POINTS = {
    "router": ("router.replica0.step", "router.replica1.step"),
    "engine": ("engine.step",),
    "replication": ("replicate.part_uploaded", "replicate.before_marker"),
}
# Inline episodes only inject raise/delay: a hang would park the inline
# caller itself and a kill would take the campaign process down — those
# two kinds belong to the subprocess episodes.
_INLINE_KINDS = ("raise", "delay")
_DELAY_SECS = "0.05"

_VOCAB = 61


class _Fleet:
    """Two pooled replica engines + a solo reference engine, built once
    per campaign (XLA compilation dominates episode cost) and sanitized
    between episodes with `Engine.abort_inflight`. Solo greedy outputs
    are memoized by ``(prompt, budget, seed)`` — engine outputs are
    batching-independent, so the memo IS the per-request ground truth."""

    def __init__(self) -> None:
        import jax

        from .. import serving
        from ..generation import GenerationConfig
        from ..models import llama

        cfg = llama.LlamaConfig.tiny(
            vocab_size=_VOCAB, max_seq_len=256, num_heads=4, num_kv_heads=2
        )
        params = llama.init(jax.random.PRNGKey(1), cfg)

        def apply(p, t, c):
            return llama.forward_with_cache(p, t, c, cfg)

        def init_cache(b, m):
            return llama.init_cache(cfg, b, m)

        def mk_engine(slots: int = 2, prefix_cache: bool = True):
            return serving.Engine(
                apply, init_cache, params, GenerationConfig(),
                slots=slots, buckets=(8,), max_len=96,
                prefix_cache=prefix_cache,
            )

        self.mk_engine = mk_engine
        self.engines = [mk_engine(), mk_engine()]
        self._solo = mk_engine(slots=1, prefix_cache=False)
        self._memo: dict = {}

    def solo(self, prompt: np.ndarray, max_new: int, seed: int) -> np.ndarray:
        key = (prompt.tobytes(), int(max_new), int(seed))
        if key not in self._memo:
            self._solo.submit(np.asarray(prompt, np.int32), max_new, seed=seed)
            (c,) = self._solo.run_until_idle()
            self._memo[key] = c.tokens
        return self._memo[key]

    def sanitize(self) -> None:
        for eng in self.engines:
            eng.abort_inflight()


def _episode_seed(seed: int, episode: int) -> int:
    return seed * 100_003 + episode


def _trace(rng: random.Random, n: int, stream) -> list:
    from .. import serving

    reqs = []
    for i in range(n):
        prompt = np.asarray(
            [rng.randrange(_VOCAB) for _ in range(rng.randint(3, 24))], np.int32
        )
        reqs.append(
            serving.Request(
                prompt=prompt,
                max_new_tokens=rng.randint(2, 5),
                rid=i,
                seed=i,
                priority=rng.choice((0, 1, 2)),
                stream=stream,
            )
        )
    return reqs


def _serving_episode(fleet: _Fleet, kind: str, ep_seed: int) -> dict:
    """One router/engine episode: seeded trace through a fresh 2-replica
    inline Router (re-admission armed) under a sampled fault env."""
    from .. import serving

    rng = random.Random(ep_seed)
    streamed: dict[int, list[int]] = {}

    def stream(rid, tok, text):
        streamed.setdefault(rid, []).append(int(tok))

    reqs = _trace(rng, rng.randint(4, 6), stream)
    refs = {r.rid: fleet.solo(r.prompt, r.max_new_tokens, r.rid) for r in reqs}

    schedule = faults.FaultSchedule(
        ep_seed, points=_POINTS[kind], kinds=_INLINE_KINDS
    )
    env = dict(schedule.env())
    env[faults.DELAY_SECS_ENV] = _DELAY_SECS

    violations: list[str] = []
    faults._reset_counters()
    fleet.sanitize()
    router = None
    try:
        with patch_environment(**env):
            router = serving.Router(
                fleet.engines,
                threads=False,
                readmit_secs=0.01,
                probation_completions=2,
                engine_factory=fleet.mk_engine,
            )
            completions = router.serve(reqs)
            # Drain invariant: preemption flips the router on the next tick
            # and admissions are refused from then on.
            _preemption.request_preemption()
            router.poll()
            if not (router.draining and router.drain_reason == "preemption"):
                violations.append("drain: preemption flag did not drain")
            try:
                router.submit(np.arange(4, dtype=np.int32), 1)
                violations.append("drain: admission accepted while draining")
            except serving.RouterDraining:
                pass
    finally:
        if router is not None:
            router.close()
        _preemption.clear_preemption()
        faults._reset_counters()

    outs = {c.rid: c for c in completions}
    if sorted(outs) != sorted(r.rid for r in reqs):
        violations.append(
            f"exactly-once: resolved rids {sorted(outs)} != submitted "
            f"{sorted(r.rid for r in reqs)}"
        )
    for c in completions:
        if c.finish_reason in ("cancelled", "failed", "shed"):
            continue
        if not np.array_equal(c.tokens, refs.get(c.rid)):
            violations.append(f"bit-identity: rid {c.rid} diverged from solo")
        want = [int(t) for t in c.tokens[: c.n_new]]
        if streamed.get(c.rid, []) != want:
            violations.append(
                f"exactly-once-stream: rid {c.rid} streamed "
                f"{streamed.get(c.rid, [])} vs tokens {want}"
            )
    m = router.metrics()
    return {
        "schedule": schedule.describe(),
        "violations": violations,
        "detail": {
            "requests": len(reqs),
            "completed": len(completions),
            "replicas_lost": m["replicas_lost"],
            "retries": m["retries"],
            "readmissions": m["readmissions"],
            "migrated_prefixes": m["migrated_prefixes"],
        },
    }


def _make_checkpoint(root: str, name: str, step: int, files: dict) -> str:
    d = os.path.join(root, name)
    os.makedirs(d, exist_ok=True)
    for rel, data in files.items():
        with open(os.path.join(d, rel), "wb") as f:
            f.write(data)
    _commit.write_manifest(d, 0, sorted(files), step=step)
    _commit.write_aggregate_manifest(d)
    with open(os.path.join(d, _commit.COMMIT_MARKER), "w") as f:
        json.dump({"version": 1, "step": step, "num_processes": 1}, f)
    return d


def _ckpt_files(rng: random.Random, n: int = 4) -> dict:
    return {
        f"part_{i}.bin": bytes([rng.randrange(256)]) * rng.randint(64, 256)
        for i in range(n)
    }


def _replication_episode(ep_seed: int) -> dict:
    """One replication episode: replicate a committed checkpoint into a
    local store under a sampled fault env, then converge cleanly — the
    remote commit marker must never exist in a torn state, and the clean
    retry must yield a restorable checkpoint. A second checkpoint sharing
    shards with the first exercises the differential (server-side copy)
    path under the same invariants."""
    rng = random.Random(ep_seed)
    violations: list[str] = []
    schedule = faults.FaultSchedule(
        ep_seed, points=_POINTS["replication"], kinds=_INLINE_KINDS
    )
    env = dict(schedule.env())
    env[faults.DELAY_SECS_ENV] = _DELAY_SECS
    detail: dict = {}
    with tempfile.TemporaryDirectory() as tmp:
        store = _replicate.LocalObjectStore(os.path.join(tmp, "store"))
        files0 = _ckpt_files(rng)
        d0 = _make_checkpoint(tmp, "checkpoint_0", 1, files0)
        rep = _replicate.Replicator(store, retries=0, timeout_secs=60)
        faults._reset_counters()
        with patch_environment(**env):
            rep.enqueue(d0)
            rep.drain(60)
        faults._reset_counters()
        marker0 = f"checkpoint_0/{_commit.COMMIT_MARKER}"
        if rep.failures and store.exists(marker0):
            violations.append(
                "torn commit: replication failed but the remote COMMIT "
                "marker exists"
            )
        # Clean converge: re-enqueue with no fault env. Remote commits are
        # final, so a previously successful upload is a no-op here.
        rep.enqueue(d0)
        rep.drain(60)
        if not store.exists(marker0):
            violations.append(
                f"lost checkpoint: clean retry did not commit "
                f"({rep.last_error})"
            )
        # Differential follow-up: half the shards unchanged.
        files1 = dict(files0)
        for rel in sorted(files1)[: len(files1) // 2]:
            files1[rel] = bytes([rng.randrange(256)]) * rng.randint(64, 256)
        d1 = _make_checkpoint(tmp, "checkpoint_1", 2, files1)
        rep.enqueue(d1)
        rep.drain(60)
        if not store.exists(f"checkpoint_1/{_commit.COMMIT_MARKER}"):
            violations.append("differential checkpoint did not commit")
        restored = _replicate.restore_latest(store, os.path.join(tmp, "restored"))
        if restored is None:
            violations.append("restore_latest found nothing restorable")
        else:
            problems = _commit.verify_checkpoint(restored)
            if problems:
                violations.append(f"restored checkpoint corrupt: {problems}")
        detail = {
            "failures": rep.failures,
            "parts_uploaded": rep.parts_uploaded,
            "parts_skipped": rep.parts_skipped,
            "parts_unchanged": rep.parts_unchanged,
            "restored": bool(restored),
        }
    return {
        "schedule": schedule.describe(),
        "violations": violations,
        "detail": detail,
    }


def _kill_episode(ep_seed: int) -> dict:
    """Out-of-process kill -9 analog: a subprocess worker replicating a
    committed checkpoint dies at ``replicate.part_uploaded`` with exit
    137; the remote must be uncommitted, and an in-process clean retry
    must converge to a restorable checkpoint."""
    rng = random.Random(ep_seed)
    violations: list[str] = []
    detail: dict = {}
    with tempfile.TemporaryDirectory() as tmp:
        store_dir = os.path.join(tmp, "store")
        d0 = _make_checkpoint(tmp, "checkpoint_0", 1, _ckpt_files(rng))
        point = f"replicate.part_uploaded@{rng.randint(1, 3)}"
        proc = subprocess.run(
            [sys.executable, "-m", "accelerate_tpu.resilience.chaos",
             "replicate", d0, store_dir],
            env=dict(faults.kill_env(point), JAX_PLATFORMS="cpu"),
            capture_output=True,
            text=True,
            timeout=300,
        )
        if proc.returncode != faults.KILL_EXIT_CODE:
            violations.append(
                f"kill worker exited {proc.returncode}, expected "
                f"{faults.KILL_EXIT_CODE}: {proc.stdout[-500:]} "
                f"{proc.stderr[-500:]}"
            )
        store = _replicate.LocalObjectStore(store_dir)
        marker = f"checkpoint_0/{_commit.COMMIT_MARKER}"
        if store.exists(marker):
            violations.append("torn commit: marker exists after kill -9")
        rep = _replicate.Replicator(store, retries=0, timeout_secs=60)
        rep.enqueue(d0)
        rep.drain(60)
        if not store.exists(marker):
            violations.append("lost checkpoint: retry after kill did not commit")
        restored = _replicate.restore_latest(store, os.path.join(tmp, "restored"))
        if restored is None or _commit.verify_checkpoint(restored):
            violations.append("restore after kill retry failed verification")
        detail = {
            "kill_point": point,
            "worker_rc": proc.returncode,
            "parts_resumed": rep.parts_skipped,
        }
    return {
        "schedule": {"seed": ep_seed, "assignments": {"kill": point}},
        "violations": violations,
        "detail": detail,
    }


def _drain_episode(ep_seed: int) -> dict:
    """Out-of-process SIGTERM drain: a threaded 2-replica router worker
    must finish in-flight work, self-check bit-identity, and exit with
    ``PREEMPTION_EXIT_CODE`` (75) — the elastic-launcher resume contract."""
    violations: list[str] = []
    proc = subprocess.Popen(
        [sys.executable, "-m", "accelerate_tpu.resilience.chaos", "serve-drain"],
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )
    tail = ""
    try:
        deadline = time.time() + 300
        for line in proc.stdout:
            tail += line
            if "SERVING" in line:
                break
            if time.time() > deadline:
                break
        if proc.poll() is not None:
            violations.append(f"drain worker exited early: {tail[-500:]}")
        else:
            time.sleep(0.5)  # let requests reach mid-decode
            proc.send_signal(signal.SIGTERM)
            tail += proc.stdout.read()
            rc = proc.wait(timeout=180)
            if rc != _preemption.PREEMPTION_EXIT_CODE:
                violations.append(
                    f"drain worker exited {rc}, expected "
                    f"{_preemption.PREEMPTION_EXIT_CODE}: {tail[-500:]}"
                )
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()
    return {
        "schedule": {"seed": ep_seed, "assignments": {"sigterm": "serve-drain"}},
        "violations": violations,
        "detail": {"rc": proc.returncode},
    }


def run_campaign(
    *,
    episodes: int = 20,
    seed: int | None = None,
    kinds: Sequence[str] = EPISODE_KINDS,
    report_path: str | None = None,
    subprocess_episodes: bool = False,
) -> dict:
    """Run a seeded chaos campaign and return the summary dict.

    ``episodes`` inline episodes rotate through ``kinds``;
    ``subprocess_episodes`` appends the kill-137 and SIGTERM-drain-75
    episodes. ``report_path`` gets one JSON line per episode. The summary
    ``digest`` is a SHA-256 over every sampled schedule — equal seeds
    produce equal digests (and equal fault assignments), which is what
    makes a failing campaign replayable."""
    if seed is None:
        try:
            seed = int(os.environ.get(faults.FAULT_SEED_ENV, "") or 0)
        except ValueError:
            seed = 0
    kinds = tuple(kinds)
    unknown = [k for k in kinds if k not in EPISODE_KINDS]
    if unknown:
        raise ValueError(
            f"unknown episode kinds {unknown}; choose from {EPISODE_KINDS}"
        )
    fleet = _Fleet() if any(k in ("router", "engine") for k in kinds) else None
    records: list[dict] = []
    for e in range(episodes):
        kind = kinds[e % len(kinds)]
        ep_seed = _episode_seed(seed, e)
        try:
            if kind == "replication":
                rec = _replication_episode(ep_seed)
            else:
                rec = _serving_episode(fleet, kind, ep_seed)
        except Exception as exc:  # an escaped exception IS a violation
            rec = {
                "schedule": faults.FaultSchedule(
                    ep_seed, points=_POINTS[kind], kinds=_INLINE_KINDS
                ).describe(),
                "violations": [f"episode crashed: {type(exc).__name__}: {exc}"],
                "detail": {},
            }
        rec.update(episode=e, kind=kind, seed=ep_seed, ok=not rec["violations"])
        _attach_postmortem(rec)
        records.append(rec)
    if subprocess_episodes:
        for kind, fn in (("replication-kill", _kill_episode),
                         ("serve-drain", _drain_episode)):
            ep_seed = _episode_seed(seed, len(records))
            rec = fn(ep_seed)
            rec.update(
                episode=len(records), kind=kind, seed=ep_seed,
                ok=not rec["violations"],
            )
            _attach_postmortem(rec)
            records.append(rec)
    digest = hashlib.sha256(
        json.dumps([r["schedule"] for r in records], sort_keys=True).encode()
    ).hexdigest()
    if report_path:
        with open(report_path, "w") as f:
            for rec in records:
                f.write(json.dumps(rec, sort_keys=True) + "\n")
    violations = [v for r in records for v in r["violations"]]
    return {
        "episodes": len(records),
        "seed": seed,
        "kinds": list(kinds),
        "ok": not violations,
        "violations": violations,
        "faulted_episodes": sum(
            1 for r in records if r["schedule"].get("assignments")
        ),
        "postmortems": [r["postmortem"] for r in records if "postmortem" in r],
        "digest": digest,
        "report_path": report_path,
    }


def _attach_postmortem(rec: dict) -> None:
    """Dump a flight-recorder bundle for a violating episode and attach
    its path to the record AND every violation string, so the triage
    trail leads straight from the campaign summary to the black box
    (`atx trace <bundle>`). No-op when the episode is clean or
    ``ATX_POSTMORTEM_DIR`` is unset."""
    if not rec["violations"]:
        return
    bundle = _flight.dump_postmortem(
        f"chaos_episode{rec.get('episode', '')}_{rec.get('kind', '')}",
        extra={"violations": rec["violations"], "schedule": rec["schedule"]},
    )
    if bundle:
        rec["postmortem"] = bundle
        rec["violations"] = [
            f"{v} [postmortem: {bundle}]" for v in rec["violations"]
        ]


# ----------------------------------------------------------- worker roles
def _replicate_worker(directory: str, store_url: str) -> int:
    rep = _replicate.Replicator(
        _replicate.store_for_url(store_url), retries=0, timeout_secs=60
    )
    rep.enqueue(directory)
    ok = rep.drain(60)
    return 0 if ok and not rep.failures else 3


def _serve_drain_worker() -> int:
    from .. import serving

    fleet = _Fleet()
    _preemption.install_preemption_handler()
    router = serving.Router(fleet.engines, engine_factory=fleet.mk_engine)
    rng = random.Random(0)
    refs: dict[int, np.ndarray] = {}

    def submit_one() -> None:
        prompt = np.asarray(
            [rng.randrange(_VOCAB) for _ in range(7)], np.int32
        )
        seed = rng.randrange(2**31 - 1)
        try:
            rid = router.submit(prompt, 4, seed=seed)
        except (serving.RouterDraining, serving.QueueFullError):
            return
        refs[rid] = fleet.solo(prompt, 4, seed)

    for _ in range(4):  # compile both replicas before announcing
        submit_one()
    router.join()
    print("SERVING", flush=True)
    deadline = time.time() + 120.0
    while not router.draining:
        if time.time() > deadline:
            print("no SIGTERM within 120s", flush=True)
            return 1
        if len(router._pending) < router.queue_depth:
            submit_one()
        router.poll(0.002)
    completions = router.pop_completions() + router.join()
    admitted_after_drain = 0
    try:
        router.submit(np.arange(7, dtype=np.int32), 4)
        admitted_after_drain = 1
    except serving.RouterDraining:
        pass
    router.close()
    mismatches = sum(
        1 for c in completions if not np.array_equal(c.tokens, refs[c.rid])
    )
    print(
        json.dumps(
            {
                "completions": len(completions),
                "mismatches": mismatches,
                "admitted_after_drain": admitted_after_drain,
                "drain_reason": router.drain_reason,
            }
        ),
        flush=True,
    )
    if mismatches or admitted_after_drain or not completions:
        return 1
    if router.drain_reason == "preemption":
        return _preemption.PREEMPTION_EXIT_CODE
    return 1


def _main(argv: Sequence[str]) -> int:
    if not argv:
        print("usage: chaos {replicate <dir> <store_url> | serve-drain}",
              file=sys.stderr)
        return 2
    if argv[0] == "replicate" and len(argv) == 3:
        return _replicate_worker(argv[1], argv[2])
    if argv[0] == "serve-drain":
        return _serve_drain_worker()
    print(f"unknown chaos worker role {argv!r}", file=sys.stderr)
    return 2


if __name__ == "__main__":  # pragma: no cover - subprocess entry
    sys.exit(_main(sys.argv[1:]))

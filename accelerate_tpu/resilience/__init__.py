"""Preemption-safe resilience layer.

TPU pods are preemptible by design: maintenance events and spot reclaims
kill whole worker groups with short notice, and a wedged collective can
park a pod forever. The reference Accelerate leans on torch-elastic's
restart semantics (PAPER.md §launcher) and assumes the bytes on disk are
sane; this layer makes a kill -9 at any instant, a SIGTERM preemption
notice, or a hung step a *recoverable* event:

- :mod:`~accelerate_tpu.resilience.commit` — the atomic checkpoint commit
  protocol (tmp-dir writes, per-file SHA-256 manifests, rename + ``COMMIT``
  marker last) plus committed-checkpoint discovery and verification.
  `checkpointing.save_state`/`load_state(resume="latest")` are built on it.
- :mod:`~accelerate_tpu.resilience.preemption` — SIGTERM/maintenance-notice
  handling: the handler only sets a flag; the training loop (or the step
  helper's automatic hook) polls it via ``accelerator.preemption_requested()``
  and turns it into an emergency checkpoint + ``PREEMPTION_EXIT_CODE`` at
  the next step boundary. The elastic loop in ``commands/launch.py`` treats
  that exit code as "resume immediately, don't burn a --max_restarts
  attempt".
- :mod:`~accelerate_tpu.resilience.watchdog` — an opt-in per-step deadline
  (``ATX_WATCHDOG_SECS``) on a heartbeat thread: when a step/collective
  wedges, it dumps every Python thread's stack and aborts the process with
  ``WATCHDOG_EXIT_CODE`` so the elastic restart fires instead of the pod
  hanging forever.
- :mod:`~accelerate_tpu.resilience.replicate` — durable checkpoint
  replication: a background `Replicator` mirrors every committed
  checkpoint into a pluggable `ObjectStore` (``ATX_REPLICATE_URL``) with
  resumable part uploads, retry/backoff, and a remote ``COMMIT`` marker
  written last; `restore_latest` brings the newest remote committed
  checkpoint back when the local root is lost. The ``gs://`` scheme is
  backed by :mod:`~accelerate_tpu.resilience.gcs` when the
  ``google-cloud-storage`` SDK is importable.
- :mod:`~accelerate_tpu.resilience.health` — opt-in peer-health watchdog
  (``ATX_HEALTH_BEAT_SECS``): collective-free heartbeat files/objects per
  process; a monitor flags stale peers (logging their last-known step) and
  escalates to the emergency-save + exit-75 elastic path in seconds instead
  of wedging until the per-step ``ATX_WATCHDOG_SECS`` deadline.
- :mod:`~accelerate_tpu.resilience.elastic` — shrink/grow-in-place
  (``ATX_ELASTIC_SHRINK``): on a health escalation or an
  ``--elastic_devices_file`` retarget, survivors run a collective-free
  agreement round (proposal/decision objects through a shared dir or the
  replicate store) and the accelerator reshards params/opt-state/step in
  memory onto the reduced mesh — seconds of reshard instead of the
  emergency-save → relaunch → restore cycle, which stays as the fallback
  whenever agreement or the reshard fails.

- :mod:`~accelerate_tpu.resilience.chaos` — seeded, replayable chaos
  campaigns (`atx chaos`): episodes sample fault schedules over the
  registered crash points and assert exactly-once/bit-identity/drain/
  no-lost-checkpoint invariants. Imported lazily (it pulls in serving);
  not re-exported here.

Fault-injection hooks (`commit.fault_point`) are no-ops unless one of the
``ATX_FAULT_{KILL,RAISE}_AT`` env vars is set; the test harness that drives
them lives in `test_utils/faults.py`. See docs/fault_tolerance.md.
"""

from .commit import (
    AGG_MANIFEST,
    COMMIT_MARKER,
    TMP_SUFFIX,
    CheckpointIntegrityWarning,
    CheckpointShardCoverageError,
    commit_dir,
    committed_checkpoints,
    fault_point,
    is_committed,
    latest_committed,
    remove_stale_tmp,
    verify_checkpoint,
    write_aggregate_manifest,
    write_manifest,
)
from .elastic import (
    AgreementError,
    ElasticController,
    TopologyDecision,
    elastic_controller_from_env,
)
from .gce import MaintenancePoller, maintenance_poller_from_env
from .health import PeerHealthMonitor, health_from_env
from .replicate import (
    LocalObjectStore,
    ObjectStore,
    ObjectStoreError,
    Replicator,
    register_store_scheme,
    remote_committed_checkpoints,
    replicator_from_env,
    restore_latest,
    store_for_url,
    store_from_env,
)
from .preemption import (
    PREEMPTION_EXIT_CODE,
    clear_preemption,
    install_preemption_handler,
    preemption_requested,
    request_preemption,
)
from .watchdog import WATCHDOG_EXIT_CODE, Watchdog, dump_all_stacks, watchdog_from_env

__all__ = [
    "AGG_MANIFEST",
    "AgreementError",
    "COMMIT_MARKER",
    "TMP_SUFFIX",
    "CheckpointIntegrityWarning",
    "CheckpointShardCoverageError",
    "ElasticController",
    "LocalObjectStore",
    "MaintenancePoller",
    "ObjectStore",
    "ObjectStoreError",
    "PREEMPTION_EXIT_CODE",
    "PeerHealthMonitor",
    "Replicator",
    "TopologyDecision",
    "WATCHDOG_EXIT_CODE",
    "Watchdog",
    "clear_preemption",
    "elastic_controller_from_env",
    "maintenance_poller_from_env",
    "commit_dir",
    "committed_checkpoints",
    "dump_all_stacks",
    "fault_point",
    "health_from_env",
    "install_preemption_handler",
    "is_committed",
    "latest_committed",
    "preemption_requested",
    "register_store_scheme",
    "remote_committed_checkpoints",
    "remove_stale_tmp",
    "replicator_from_env",
    "request_preemption",
    "restore_latest",
    "store_for_url",
    "store_from_env",
    "verify_checkpoint",
    "watchdog_from_env",
    "write_aggregate_manifest",
    "write_manifest",
]

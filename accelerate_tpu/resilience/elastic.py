"""Shrink/grow-in-place: live topology agreement for elastic training.

PR 10 made *restores* topology-independent; this module removes the
restart from the loop. When the peer-health watchdog (`health.py`) flags a
dead peer — or ``--elastic_devices_file`` retargets the group — survivors
run a **collective-free agreement round** and then reshard live state in
memory (`checkpointing.reshard_arrays`) instead of round-tripping through
emergency-save → exit 75 → relaunch → restore.

Agreement protocol (the ATX502-safe pattern from the preemption or-reduce
and the PR-9 sentinel polling — proposal *objects*, never collectives,
because the dead peer would park any collective forever):

- Every survivor writes ``proposal_<rank>.json`` = ``(epoch, survivors,
  host_devices, step)`` to the agreement surface (a shared directory or
  the replicate object store under ``elastic/``).
- The **coordinator** (lowest-ranked survivor) polls until every proposed
  survivor has posted an *identical* proposal for this epoch, then writes
  ``decision_<epoch>.json`` — the write is idempotent, so replays and
  races are safe.
- Non-coordinators poll for the decision and verify it matches their own
  proposal. Any mismatch (different survivor sets, different steps — the
  group diverged) or timeout (``ATX_ELASTIC_AGREE_SECS``) raises
  `AgreementError`, and the caller degrades to the existing
  emergency-save + exit-75 relaunch path. Agreement can fail; it cannot
  wedge or split-brain.

Epochs are monotonically increasing per transition; proposals from older
epochs are ignored (a crashed round's debris), and decisions are keyed by
epoch so a late reader of round N never adopts round N+1's topology by
accident. Grow-back is the same round in reverse, triggered by
``--elastic_devices_file`` reporting more capacity or a retired peer's
beats returning.

Survivor ranks are the **old** ranks (a shrink of {0..7} losing {2,5}
leaves roster (0,1,3,4,6,7)) — beat files and ``node_<p>/`` store
prefixes stay valid — while `TopologyDecision.rank_of` gives the dense
new rank used to re-initialize the distributed runtime.

Like `health.py` and `commit.py`, this module is jax-free: the accelerator
owns all mesh/array work; everything here is file/store IO and is
deterministically testable with injected clocks.

Knobs: ``ATX_ELASTIC_SHRINK`` (opt-in), ``ATX_ELASTIC_AGREE_SECS``
(agreement timeout, default 30), ``ATX_ELASTIC_DIR`` (surface override),
``ATX_ELASTIC_DEVICES_FILE`` (grow/shrink target file, set by the
launcher's ``--elastic_devices_file``), ``ATX_ELASTIC_PEERS`` (roster
size override for simulated-peer tests, like ``ATX_HEALTH_PEERS``).
"""

from __future__ import annotations

import json
import logging
import os
import sys
import time
from dataclasses import dataclass
from typing import Any, Callable, Iterable

from .commit import fault_point
from .preemption import request_preemption

logger = logging.getLogger(__name__)

PROPOSAL_FILE = "proposal_{proc}.json"
DECISION_FILE = "decision_{epoch}.json"
STORE_PREFIX = "elastic/"

ELASTIC_SHRINK_ENV = "ATX_ELASTIC_SHRINK"
AGREE_SECS_ENV = "ATX_ELASTIC_AGREE_SECS"
DEVICES_FILE_ENV = "ATX_ELASTIC_DEVICES_FILE"
ELASTIC_DIR_ENV = "ATX_ELASTIC_DIR"
ELASTIC_PEERS_ENV = "ATX_ELASTIC_PEERS"


class AgreementError(RuntimeError):
    """The survivors could not agree on a topology (timeout, divergent
    proposals, or a conflicting decision) — shrink-in-place must not
    proceed; degrade to the relaunch path."""


@dataclass(frozen=True)
class TopologyDecision:
    """The agreed post-transition topology: all survivors adopt the same
    ``(survivors, host_devices, epoch)`` before touching any state."""

    epoch: int
    survivors: tuple[int, ...]
    host_devices: int
    step: int

    @property
    def num_processes(self) -> int:
        return len(self.survivors)

    @property
    def num_devices(self) -> int:
        return len(self.survivors) * self.host_devices

    def rank_of(self, old_rank: int) -> int | None:
        """Dense new rank of ``old_rank`` (its index in the survivor list),
        None when the rank did not survive."""
        try:
            return self.survivors.index(old_rank)
        except ValueError:
            return None

    def to_payload(self) -> dict[str, Any]:
        return {
            "epoch": self.epoch,
            "survivors": list(self.survivors),
            "host_devices": self.host_devices,
            "step": self.step,
        }

    @classmethod
    def from_payload(cls, payload: dict[str, Any]) -> "TopologyDecision":
        return cls(
            epoch=int(payload["epoch"]),
            survivors=tuple(int(p) for p in payload["survivors"]),
            host_devices=int(payload["host_devices"]),
            step=int(payload["step"]),
        )

    def same_topology(self, other: "TopologyDecision") -> bool:
        return (
            self.epoch == other.epoch
            and self.survivors == other.survivors
            and self.host_devices == other.host_devices
            and self.step == other.step
        )


# ----------------------------------------------------------------- surfaces
class _FileSurface:
    """Agreement objects as files in a shared directory."""

    def __init__(self, directory: str):
        self.directory = directory

    def write(self, name: str, payload: dict[str, Any]) -> None:
        os.makedirs(self.directory, exist_ok=True)
        path = os.path.join(self.directory, name)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, path)  # readers never see a partial proposal

    def read(self, name: str) -> dict[str, Any] | None:
        try:
            with open(os.path.join(self.directory, name)) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def delete(self, name: str) -> None:
        try:
            os.remove(os.path.join(self.directory, name))
        except OSError:
            pass

    def __repr__(self) -> str:  # pragma: no cover - logging only
        return f"_FileSurface({self.directory!r})"


class _StoreSurface:
    """Agreement objects in the replicate store (per-node filesystems)."""

    def __init__(self, store, prefix: str = STORE_PREFIX):
        self.store = store
        self.prefix = prefix

    def write(self, name: str, payload: dict[str, Any]) -> None:
        self.store.put_bytes(json.dumps(payload).encode(), self.prefix + name)

    def read(self, name: str) -> dict[str, Any] | None:
        try:
            return json.loads(self.store.get_bytes(self.prefix + name).decode())
        except Exception:
            return None

    def delete(self, name: str) -> None:
        try:
            self.store.delete(self.prefix + name)
        except Exception:
            pass

    def __repr__(self) -> str:  # pragma: no cover - logging only
        return f"_StoreSurface({self.store!r})"


def post_peer_proposals(
    surface,
    peers: Iterable[int],
    decision: TopologyDecision,
) -> None:
    """Write ``decision`` as the proposal of each rank in ``peers`` — how
    tests and the lint replay seed the simulated survivors' side of an
    agreement round (the real peers would have written these themselves)."""
    for p in peers:
        payload = decision.to_payload()
        payload["proposer"] = int(p)
        surface.write(PROPOSAL_FILE.format(proc=int(p)), payload)


# ---------------------------------------------------------------- agreement
class ElasticAgreement:
    """One agreement round: propose, then converge on a decision."""

    def __init__(
        self,
        surface,
        process_index: int,
        *,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
        poll_secs: float = 0.05,
    ):
        self.surface = surface
        self.process_index = int(process_index)
        self._clock = clock
        self._sleep = sleep
        self.poll_secs = float(poll_secs)

    def agree(self, proposal: TopologyDecision, timeout: float) -> TopologyDecision:
        """Run one round for ``proposal``; returns the adopted decision or
        raises `AgreementError`. Never issues a collective."""
        payload = proposal.to_payload()
        payload["proposer"] = self.process_index
        self.surface.write(PROPOSAL_FILE.format(proc=self.process_index), payload)
        fault_point("shrink.agreement_proposed")
        deadline = self._clock() + float(timeout)
        coordinator = min(proposal.survivors)
        if self.process_index == coordinator:
            return self._coordinate(proposal, deadline)
        return self._follow(proposal, deadline)

    def _coordinate(
        self, proposal: TopologyDecision, deadline: float
    ) -> TopologyDecision:
        decision_name = DECISION_FILE.format(epoch=proposal.epoch)
        while True:
            missing: list[int] = []
            for peer in proposal.survivors:
                raw = self.surface.read(PROPOSAL_FILE.format(proc=peer))
                if raw is None:
                    missing.append(peer)
                    continue
                try:
                    theirs = TopologyDecision.from_payload(raw)
                except (KeyError, TypeError, ValueError):
                    missing.append(peer)
                    continue
                if theirs.epoch < proposal.epoch:
                    missing.append(peer)  # stale debris from an older round
                    continue
                if not theirs.same_topology(proposal):
                    raise AgreementError(
                        f"survivor {peer} proposed a conflicting topology "
                        f"{raw} vs ours {proposal.to_payload()} — the group "
                        "diverged; refusing to shrink in place"
                    )
            if not missing:
                # Idempotent: a replayed/raced coordinator rewrites the
                # identical bytes, so "decision already exists" is not a
                # conflict unless the content differs.
                existing = self.surface.read(decision_name)
                if existing is not None:
                    theirs = TopologyDecision.from_payload(existing)
                    if not theirs.same_topology(proposal):
                        raise AgreementError(
                            f"decision for epoch {proposal.epoch} already "
                            f"exists with different topology {existing}"
                        )
                    return theirs
                payload = proposal.to_payload()
                payload["coordinator"] = self.process_index
                self.surface.write(decision_name, payload)
                return proposal
            if self._clock() >= deadline:
                raise AgreementError(
                    f"agreement timed out after {deadline}: no proposal from "
                    f"survivors {missing} for epoch {proposal.epoch}"
                )
            self._sleep(self.poll_secs)

    def _follow(self, proposal: TopologyDecision, deadline: float) -> TopologyDecision:
        decision_name = DECISION_FILE.format(epoch=proposal.epoch)
        while True:
            raw = self.surface.read(decision_name)
            if raw is not None:
                try:
                    decision = TopologyDecision.from_payload(raw)
                except (KeyError, TypeError, ValueError) as e:
                    raise AgreementError(f"unreadable decision {raw}: {e}")
                if not decision.same_topology(proposal):
                    raise AgreementError(
                        f"coordinator decided {raw} but this process proposed "
                        f"{proposal.to_payload()} — divergent view of the "
                        "group; refusing to shrink in place"
                    )
                return decision
            if self._clock() >= deadline:
                raise AgreementError(
                    f"agreement timed out: no decision for epoch "
                    f"{proposal.epoch} (coordinator "
                    f"{min(proposal.survivors)} silent)"
                )
            self._sleep(self.poll_secs)


# --------------------------------------------------------------- controller
class ElasticController:
    """Step-boundary shrink/grow decision engine (jax-free).

    `check(step)` is called by the accelerator at every step entry; it
    returns a `TopologyDecision` when the group just agreed to resize (the
    accelerator then reshards and calls `adopt`), None otherwise, and
    raises `AgreementError` when a triggered round failed (the accelerator
    then falls back to the emergency-save + exit-75 path).

    Triggers, in priority order:

    1. health escalation: `PeerHealthMonitor.stale_peers` ∩ roster — the
       survivors drop the dead ranks (pure shrink);
    2. the devices file (``ATX_ELASTIC_DEVICES_FILE``): ``"P H"`` retargets
       to P processes x H devices (``"H"`` alone keeps the process count —
       the launcher's original format); shrink keeps the lowest current
       ranks, grow re-adds the lowest retired ranks first;
    3. returning peer beats: a retired rank heartbeating again (beat
       timestamp newer than its retirement) is absorbed back.
    """

    def __init__(
        self,
        surface,
        process_index: int,
        num_processes: int,
        host_devices: int,
        *,
        agree_secs: float = 30.0,
        devices_file: str | None = None,
        health=None,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
    ):
        self.surface = surface
        self.process_index = int(process_index)
        self.roster: tuple[int, ...] = tuple(range(int(num_processes)))
        self.initial_roster = self.roster
        self.host_devices = int(host_devices)
        self.agree_secs = float(agree_secs)
        self.devices_file = devices_file
        self.health = health
        self._clock = clock
        self.agreement = ElasticAgreement(
            surface, self.process_index, clock=clock, sleep=sleep
        )
        self.epoch = 0
        self.escalated_at: float | None = None
        self.last_transition: dict[str, Any] | None = None
        self._retired_at: dict[int, float] = {}
        self._abandoned = False
        self.transitions = 0
        # Registry mirrors (docs/observability.md): shrink/grow counts and
        # the live world size, fleet-visible on /metrics.
        from .. import telemetry as _telemetry

        self._c_shrinks = _telemetry.counter(
            "elastic_shrinks", "In-place topology shrinks adopted")
        self._c_grows = _telemetry.counter(
            "elastic_grows", "In-place topology grows adopted")
        self._h_agree = _telemetry.histogram(
            "elastic_agree_ms", "Escalation-to-adoption agreement wall (ms)")
        self._g_world = _telemetry.gauge(
            "elastic_world_size", "Processes in the agreed roster", aggregate="max")
        self._g_world.set(len(self.roster))

    # -- triggers ------------------------------------------------------------
    def _read_devices_file(self) -> tuple[int, int] | None:
        """Parse the target as ``(num_processes, host_devices)``. One int
        means host_devices only (the launcher's original format). Unreadable
        or torn writes keep the previous target (None)."""
        path = self.devices_file
        if not path:
            return None
        try:
            with open(path) as f:
                parts = f.read().split()
        except OSError:
            return None
        try:
            if len(parts) == 1:
                procs, devices = len(self.roster), int(parts[0])
            elif len(parts) >= 2:
                procs, devices = int(parts[0]), int(parts[1])
            else:
                return None
        except ValueError:
            return None
        if procs <= 0 or devices <= 0:
            return None
        return procs, devices

    def _returning_peers(self) -> set[int]:
        backend = getattr(self.health, "backend", None)
        if backend is None or not self._retired_at:
            return set()
        back: set[int] = set()
        for peer, retired in list(self._retired_at.items()):
            payload = backend.read(peer)
            if payload is None:
                continue
            try:
                beat_time = float(payload.get("time", 0.0))
            except (TypeError, ValueError):
                continue
            # Wall time on purpose: retirement stamps wall time too, and the
            # comparison is against the SAME peer's pre/post-death beats.
            if beat_time > retired + 1.0:
                back.add(peer)
        return back

    def _retire_self(self, target: tuple[int, ...]) -> None:
        sys.stderr.write(
            f"[atx elastic] rank {self.process_index} is not in the target "
            f"roster {target}; requesting preemption (emergency save + "
            "exit 75) to drain this process\n"
        )
        sys.stderr.flush()
        self._abandoned = True
        request_preemption()

    def _trigger(self) -> tuple[tuple[int, ...], int, str] | None:
        roster_set = set(self.roster)
        stale = (
            set(self.health.stale_peers) & roster_set
            if self.health is not None
            else set()
        )
        if stale:
            survivors = tuple(p for p in self.roster if p not in stale)
            if not survivors or self.process_index not in survivors:
                return None
            return survivors, self.host_devices, "shrink"
        target = self._read_devices_file()
        if target is not None:
            procs, devices = target
            if (procs, devices) != (len(self.roster), self.host_devices):
                if procs <= len(self.roster):
                    survivors = tuple(sorted(roster_set))[:procs]
                else:
                    pool = sorted(roster_set | set(self.initial_roster))
                    while len(pool) < procs:
                        pool.append(pool[-1] + 1 if pool else 0)
                    survivors = tuple(pool[:procs])
                if self.process_index not in survivors:
                    self._retire_self(survivors)
                    return None
                grow = procs * devices > len(self.roster) * self.host_devices
                return survivors, devices, ("grow" if grow else "shrink")
        returning = self._returning_peers()
        if returning:
            survivors = tuple(sorted(roster_set | returning))
            return survivors, self.host_devices, "grow"
        return None

    # -- main entry ----------------------------------------------------------
    def check(self, step: int) -> TopologyDecision | None:
        """One step-boundary poll: None (nothing to do) or an agreed
        decision. Raises `AgreementError` on a failed round — after which
        the controller disarms itself (the caller is now on the relaunch
        path and must not re-enter agreement every step)."""
        if self._abandoned:
            return None
        trig = self._trigger()
        if trig is None:
            return None
        survivors, host_devices, kind = trig
        if self.escalated_at is None:
            self.escalated_at = self._clock()
            logger.warning(
                "[atx elastic] %s escalation at step %d: target %d proc(s) "
                "x %d device(s), roster %r",
                kind,
                step,
                len(survivors),
                host_devices,
                self.roster,
            )
        proposal = TopologyDecision(
            epoch=self.epoch + 1,
            survivors=survivors,
            host_devices=host_devices,
            step=int(step),
        )
        try:
            return self.agreement.agree(proposal, timeout=self.agree_secs)
        except AgreementError:
            self._abandoned = True
            raise

    def adopt(self, decision: TopologyDecision) -> None:
        """Commit the controller's view after the accelerator finished the
        reshard: new roster/epoch, retirement stamps for departed ranks
        (the returning-beat grow trigger keys off these)."""
        old = set(self.roster)
        self.epoch = decision.epoch
        self.roster = decision.survivors
        self.host_devices = decision.host_devices
        now = time.time()
        for p in old - set(decision.survivors):
            self._retired_at[p] = now
        for p in set(decision.survivors) - old:
            self._retired_at.pop(p, None)
        agree_secs = (
            self._clock() - self.escalated_at
            if self.escalated_at is not None
            else 0.0
        )
        self.escalated_at = None
        self.transitions += 1
        if len(decision.survivors) < len(old):
            self._c_shrinks.inc()
        elif len(decision.survivors) > len(old):
            self._c_grows.inc()
        self._h_agree.observe(agree_secs * 1e3)
        self._g_world.set(len(decision.survivors))
        self.last_transition = {
            "epoch": decision.epoch,
            "survivors": decision.survivors,
            "host_devices": decision.host_devices,
            "step": decision.step,
            "agree_secs": agree_secs,
        }

    def abandon(self) -> None:
        """Disarm after a failed in-place transition (the caller degraded to
        the relaunch path)."""
        self._abandoned = True


# ----------------------------------------------------------------- env entry
def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        return default


def elastic_controller_from_env(
    *,
    root: str | None = None,
    store=None,
    health=None,
    process_index: int = 0,
    num_processes: int = 1,
    host_devices: int = 1,
    total_devices: int | None = None,
) -> ElasticController | None:
    """Build the controller from the env contract; None unless
    ``ATX_ELASTIC_SHRINK`` is truthy (opt-in, like the health monitor).

    Agreement surface precedence mirrors `health_from_env`:
    ``ATX_ELASTIC_DIR`` > replicate ``store`` (under ``elastic/``) >
    ``<root>/.elastic``. No surface → disabled with a warning."""
    flag = os.environ.get(ELASTIC_SHRINK_ENV, "").strip().lower()
    if flag not in ("1", "true", "yes", "on"):
        return None
    peers_override = os.environ.get(ELASTIC_PEERS_ENV, "").strip()
    if peers_override:
        try:
            num_processes = int(peers_override)
        except ValueError:
            pass
    if total_devices is not None and num_processes > 0:
        # Simulated-peer worlds (ATX_ELASTIC_PEERS > real process count):
        # "per-host" devices is the roster's even share of the mesh.
        if total_devices % num_processes == 0:
            host_devices = total_devices // num_processes
    elastic_dir = os.environ.get(ELASTIC_DIR_ENV, "").strip()
    if elastic_dir:
        surface = _FileSurface(elastic_dir)
    elif store is not None:
        surface = _StoreSurface(store)
    elif root:
        surface = _FileSurface(os.path.join(root, ".elastic"))
    else:
        logger.warning(
            "[atx elastic] %s set but no agreement surface (no %s, no "
            "replicate store, no checkpoint root); shrink-in-place disabled",
            ELASTIC_SHRINK_ENV,
            ELASTIC_DIR_ENV,
        )
        return None
    devices_file = os.environ.get(DEVICES_FILE_ENV, "").strip() or None
    return ElasticController(
        surface,
        process_index,
        num_processes,
        host_devices,
        agree_secs=_env_float(AGREE_SECS_ENV, 30.0),
        devices_file=devices_file,
        health=health,
    )

"""Real ``gs://`` ObjectStore for checkpoint replication.

Thin wrapper over the official ``google-cloud-storage`` SDK implementing
the `ObjectStore` contract (`resilience/replicate.py`): atomic writes (GCS
object creation is atomic by construction), stat with size (GCS reports
md5/crc32c, not SHA-256, so ``ObjectStat.sha256`` is None and the
Replicator's resumable-skip check falls back to size-only — the final
`verify_checkpoint` after a restore still hashes every byte), recursive
prefix listing, and deletes.

The SDK import is **lazy and gated**: this module imports cleanly on
machines without the SDK, and only `GcsObjectStore` construction raises —
with an actionable message — when ``google.cloud.storage`` is missing.
``store_for_url("gs://bucket/prefix")`` routes here automatically via the
scheme registry; tests inject a fake SDK client, so the wrapper is
exercised without network or credentials.
"""

from __future__ import annotations

import os
from typing import Any

from .replicate import ObjectStat, ObjectStore, ObjectStoreError

_MISSING_SDK_MSG = (
    "gs:// replication needs the `google-cloud-storage` package, which is "
    "not importable in this environment ({error}). Either install it "
    "(`pip install google-cloud-storage`) or mount the bucket with gcsfuse "
    "and point ATX_REPLICATE_URL at the mount path to use the filesystem "
    "store instead."
)


def _load_sdk():
    try:
        from google.cloud import storage  # type: ignore[import-not-found]
    except ImportError as e:
        raise ObjectStoreError(_MISSING_SDK_MSG.format(error=e)) from e
    return storage


def parse_gs_url(url: str) -> tuple[str, str]:
    """``gs://bucket[/prefix]`` -> ``(bucket, prefix)``; the prefix is
    normalized to either ``""`` or ``"...segments.../"`` so key joins are a
    plain concatenation."""
    if url.startswith("gs://"):
        rest = url[len("gs://") :]
    else:
        rest = url.lstrip("/")
    if not rest:
        raise ObjectStoreError(f"gs:// URL {url!r} names no bucket")
    bucket, _, prefix = rest.partition("/")
    prefix = prefix.strip("/")
    return bucket, f"{prefix}/" if prefix else ""


class GcsObjectStore(ObjectStore):
    """`ObjectStore` over one GCS bucket (+ optional key prefix).

    ``client`` is injectable for tests (any object with the
    ``google.cloud.storage.Client`` surface: ``bucket(name)`` returning
    buckets with ``blob(name)``/``list_blobs``); when omitted the real SDK
    client is constructed — which is the point where missing-SDK and
    missing-credentials errors surface, with clear messages.
    """

    def __init__(self, bucket: str, prefix: str = "", *, client: Any = None):
        if client is None:
            storage = _load_sdk()
            try:
                client = storage.Client()
            except Exception as e:
                raise ObjectStoreError(
                    f"could not construct a GCS client for bucket {bucket!r}: "
                    f"{e} — configure application-default credentials "
                    "(GOOGLE_APPLICATION_CREDENTIALS or `gcloud auth "
                    "application-default login`)"
                ) from e
        self.client = client
        self.bucket_name = bucket
        self.prefix = prefix
        self._bucket = client.bucket(bucket)

    @classmethod
    def from_url(cls, url: str, *, client: Any = None) -> "GcsObjectStore":
        bucket, prefix = parse_gs_url(url)
        return cls(bucket, prefix, client=client)

    def _blob(self, key: str):
        return self._bucket.blob(self.prefix + key)

    def put_file(self, local_path: str, key: str) -> None:
        self._blob(key).upload_from_filename(local_path)

    def put_bytes(self, data: bytes, key: str) -> None:
        self._blob(key).upload_from_string(data)

    def get_file(self, key: str, local_path: str) -> None:
        os.makedirs(os.path.dirname(os.path.abspath(local_path)), exist_ok=True)
        # Download into a sibling tmp + rename so a crashed download never
        # leaves a partial file where the restore path expects a whole one.
        tmp = f"{local_path}.get.{os.getpid()}"
        try:
            self._blob(key).download_to_filename(tmp)
        except Exception as e:
            try:
                os.remove(tmp)
            except OSError:
                pass
            raise self._translate(e, key)
        os.replace(tmp, local_path)

    def get_bytes(self, key: str) -> bytes:
        try:
            return self._blob(key).download_as_bytes()
        except Exception as e:
            raise self._translate(e, key)

    def get_range(self, key: str, start: int, length: int) -> bytes:
        if start < 0 or length < 0:
            raise ValueError(f"invalid range start={start} length={length}")
        if length == 0:
            return b""
        try:
            # GCS ranges are INCLUSIVE of `end`; an end past the object is
            # clamped server-side, matching the file-read suffix contract.
            return self._blob(key).download_as_bytes(
                start=start, end=start + length - 1
            )
        except Exception as e:
            raise self._translate(e, key)

    def stat(self, key: str) -> ObjectStat | None:
        blob = self._bucket.get_blob(self.prefix + key)
        if blob is None:
            return None
        return ObjectStat(size=int(blob.size or 0), sha256=None)

    def list(self, prefix: str = "") -> list[str]:
        full = self.prefix + prefix
        out = []
        for blob in self.client.list_blobs(self.bucket_name, prefix=full):
            name = blob.name
            if name.startswith(self.prefix):
                out.append(name[len(self.prefix) :])
        return sorted(out)

    def delete(self, key: str) -> None:
        try:
            self._blob(key).delete()
        except Exception as e:
            if self._is_not_found(e):
                return
            raise

    def _translate(self, e: Exception, key: str) -> Exception:
        if self._is_not_found(e):
            return ObjectStoreError(
                f"no object {key!r} in gs://{self.bucket_name}/{self.prefix}"
            )
        return e

    @staticmethod
    def _is_not_found(e: Exception) -> bool:
        # Avoid a hard dependency on google.api_core exception classes: any
        # client error carrying a 404 code (the real NotFound does) counts.
        return getattr(e, "code", None) == 404 or type(e).__name__ == "NotFound"

    def __repr__(self) -> str:
        return f"GcsObjectStore(gs://{self.bucket_name}/{self.prefix})"

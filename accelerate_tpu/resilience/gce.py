"""GCE maintenance-event metadata poller → `request_preemption()`.

SIGTERM is not the only preemption notice on GCE: host maintenance events
and spot reclaims are announced on the instance metadata server
(``maintenance-event`` flips from ``NONE``; ``preempted`` flips to
``TRUE``) — often *earlier* than the TERM signal reaches the process. This
poller watches both endpoints from a daemon thread and, on the first
non-benign value, feeds `resilience.request_preemption()` so the training
loop writes its emergency checkpoint with the full grace window instead of
the signal-to-kill remainder.

Off by default. ``ATX_GCE_PREEMPT_POLL_SECS=<seconds>`` (> 0) enables it —
`Accelerator.__init__` calls `maintenance_poller_from_env()` alongside the
SIGTERM handler install. ``ATX_GCE_METADATA_URL`` overrides the metadata
base URL (the unit tests point it at a stub HTTP server). Requests carry
the mandatory ``Metadata-Flavor: Google`` header; network errors are
treated as "not on GCE" and simply retried on the next tick — the poller
must never take down a training process.
"""

from __future__ import annotations

import logging
import os
import threading
import urllib.error
import urllib.request
from typing import Callable

from .preemption import request_preemption

logger = logging.getLogger(__name__)

DEFAULT_METADATA_URL = (
    "http://metadata.google.internal/computeMetadata/v1/instance"
)
# maintenance-event values that do NOT announce an upcoming disruption.
_BENIGN_MAINTENANCE = ("", "NONE")


def _read_endpoint(base_url: str, name: str, timeout: float) -> str | None:
    req = urllib.request.Request(
        f"{base_url.rstrip('/')}/{name}",
        headers={"Metadata-Flavor": "Google"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.read().decode("utf-8", "replace").strip()
    except (urllib.error.URLError, OSError, ValueError):
        return None  # not on GCE / transient — retry next tick


class MaintenancePoller:
    """Daemon thread polling the metadata server until a preemption notice
    appears (then fires ``on_preempt`` once and stops) or `stop()`."""

    def __init__(
        self,
        poll_secs: float,
        metadata_url: str = DEFAULT_METADATA_URL,
        on_preempt: Callable[[], None] = request_preemption,
        request_timeout: float = 2.0,
    ) -> None:
        if poll_secs <= 0:
            raise ValueError("poll_secs must be > 0 (the poller is opt-in)")
        self.poll_secs = float(poll_secs)
        self.metadata_url = metadata_url
        self.on_preempt = on_preempt
        self.request_timeout = float(request_timeout)
        self.notice: str | None = None  # what tripped the poller, for logs
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # ------------------------------------------------------------------ poll
    def check_once(self) -> str | None:
        """One metadata sweep; returns the notice string (and records it)
        when a disruption is announced, else None."""
        event = _read_endpoint(
            self.metadata_url, "maintenance-event", self.request_timeout
        )
        if event is not None and event.upper() not in _BENIGN_MAINTENANCE:
            self.notice = f"maintenance-event={event}"
            return self.notice
        preempted = _read_endpoint(
            self.metadata_url, "preempted", self.request_timeout
        )
        if preempted is not None and preempted.upper() == "TRUE":
            self.notice = "preempted=TRUE"
            return self.notice
        return None

    def _run(self) -> None:
        while not self._stop.is_set():
            notice = self.check_once()
            if notice is not None:
                logger.warning(
                    "GCE metadata announced %s — requesting preemption "
                    "(emergency checkpoint at the next step boundary)",
                    notice,
                )
                self.on_preempt()
                return
            self._stop.wait(self.poll_secs)

    # --------------------------------------------------------------- control
    def start(self) -> "MaintenancePoller":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="atx-gce-maintenance-poller", daemon=True
            )
            self._thread.start()
        return self

    def stop(self, join_timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=join_timeout)
            self._thread = None

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()


def maintenance_poller_from_env() -> MaintenancePoller | None:
    """Start a poller iff ``ATX_GCE_PREEMPT_POLL_SECS`` > 0 (off by
    default); ``ATX_GCE_METADATA_URL`` overrides the server for tests."""
    raw = os.environ.get("ATX_GCE_PREEMPT_POLL_SECS", "").strip()
    if not raw:
        return None
    try:
        poll_secs = float(raw)
    except ValueError:
        logger.warning(
            "ATX_GCE_PREEMPT_POLL_SECS=%r is not a number; GCE maintenance "
            "polling stays off",
            raw,
        )
        return None
    if poll_secs <= 0:
        return None
    url = os.environ.get("ATX_GCE_METADATA_URL", DEFAULT_METADATA_URL)
    return MaintenancePoller(poll_secs, metadata_url=url).start()

"""Hang watchdog: a per-step deadline on a heartbeat thread.

A wedged collective (one host died mid-all-reduce, a deadlocked rendezvous)
parks a TPU pod silently — the process never exits, so the elastic restart
in ``commands/launch.py`` never fires and the pod burns until a human
notices. The watchdog converts the hang into a crash the launcher can
handle: a daemon thread checks an armed deadline; when a step exceeds it,
every Python thread's stack is dumped to stderr (so the wedge site is in
the log) and the process aborts with ``WATCHDOG_EXIT_CODE``.

Opt-in via ``ATX_WATCHDOG_SECS=<per-step deadline>``; the step helper
returned by ``Accelerator.make_train_step`` re-arms the countdown at every
step ENTRY and leaves it armed across the call — heartbeat semantics. jax
dispatches compiled steps *asynchronously* (the Python call can return
before the device work runs), so a disarm-on-return would miss a wedged
collective entirely; instead the deadline bounds the gap between
consecutive step entries, which catches the wedge wherever the process
actually stalls (blocking on the step's metrics, the next dispatch, or
interpreter exit). The FIRST armed step of a process gets
``ATX_WATCHDOG_FIRST_STEP_SECS`` (default 10x the deadline) to absorb XLA
compilation; ``Accelerator.end_training()`` stands the watchdog down so
post-training work is never shot.

Direct use for custom loops::

    wd = Watchdog(deadline_secs=120)
    for batch in loader:
        wd.arm()                                 # re-arms every iteration
        state, metrics = my_step(state, batch)
        print(float(metrics["loss"]))            # wedge -> no next arm -> abort
    wd.stop()
"""

from __future__ import annotations

import contextlib
import os
import sys
import threading
import time
import traceback
from typing import Any, Callable, Iterator

WATCHDOG_EXIT_CODE = 114


def dump_all_stacks(out: Any) -> None:
    """Write every live Python thread's stack to ``out`` (pure Python via
    ``sys._current_frames`` so it works on any file-like object; the frames
    of a thread blocked in a C call show the last Python frame — the
    jitted-step dispatch site — which is exactly the wedge evidence)."""
    frames = sys._current_frames()
    for thread in threading.enumerate():
        out.write(
            f"\n--- thread {thread.name!r} (ident={thread.ident}, "
            f"daemon={thread.daemon}) ---\n"
        )
        frame = frames.get(thread.ident)
        if frame is None:
            out.write("  <no frame>\n")
            continue
        out.write("".join(traceback.format_stack(frame)))
    out.flush()


class Watchdog:
    """Heartbeat-thread deadline. `arm()` starts the countdown, `disarm()`
    stops it, `beat()` restarts it without counting a new step (for long
    host-side loops between device steps)."""

    def __init__(
        self,
        deadline_secs: float,
        *,
        first_deadline_secs: float | None = None,
        out: Any = None,
        abort: Callable[[], None] | None = None,
    ) -> None:
        self.deadline = float(deadline_secs)
        if self.deadline <= 0:
            raise ValueError(f"deadline_secs must be > 0, got {deadline_secs}")
        self.first_deadline = (
            max(float(first_deadline_secs), self.deadline)
            if first_deadline_secs is not None
            else self.deadline
        )
        self._out = out
        self._abort = abort  # test seam: called instead of os._exit
        self._lock = threading.Lock()
        self._armed_at: float | None = None
        self._armed_deadline = self.deadline
        self._arms = 0
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        self.fired = threading.Event()

    def arm(self, deadline_secs: float | None = None) -> None:
        """Start the countdown for one step. The first arm of this watchdog
        uses the (longer) first-step deadline — compilation headroom."""
        with self._lock:
            if deadline_secs is not None:
                d = float(deadline_secs)
            elif self._arms == 0:
                d = self.first_deadline
            else:
                d = self.deadline
            self._arms += 1
            self._armed_deadline = d
            self._armed_at = time.monotonic()
            self._ensure_thread_locked()

    def beat(self) -> None:
        with self._lock:
            if self._armed_at is not None:
                self._armed_at = time.monotonic()

    def disarm(self) -> None:
        with self._lock:
            self._armed_at = None

    @contextlib.contextmanager
    def paused(self) -> Iterator[None]:
        """Suspend the deadline across legitimate long host work — a
        synchronous ``save_state``/``load_state`` between steps routinely
        exceeds a per-step deadline, and shooting the process mid-commit
        would lose the in-flight checkpoint AND burn a restart attempt.
        On exit the countdown restarts (heartbeat semantics) iff it was
        armed on entry; pausing an unarmed watchdog never arms it."""
        with self._lock:
            was_armed = self._armed_at is not None
            self._armed_at = None
        try:
            yield
        finally:
            if was_armed:
                with self._lock:
                    self._armed_at = time.monotonic()

    def stop(self) -> None:
        """Shut the heartbeat thread down (tests / end of training)."""
        self.disarm()
        self._stop.set()
        thread = self._thread
        if thread is not None and thread.is_alive():
            thread.join(timeout=2.0)
        self._thread = None

    # ------------------------------------------------------------- internals
    def _ensure_thread_locked(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="atx-watchdog", daemon=True
            )
            self._thread.start()

    def _poll_interval(self) -> float:
        return min(max(self.deadline / 4.0, 0.02), 1.0)

    def _run(self) -> None:
        while not self._stop.wait(self._poll_interval()):
            with self._lock:
                armed_at = self._armed_at
                deadline = self._armed_deadline
            if armed_at is not None and time.monotonic() - armed_at > deadline:
                self._fire(deadline)
                return

    def _fire(self, deadline: float) -> None:
        out = self._out if self._out is not None else sys.stderr
        try:
            out.write(
                f"\n[atx watchdog] step exceeded its {deadline:.1f}s deadline "
                "(ATX_WATCHDOG_SECS): a step or collective appears wedged. "
                "Dumping all thread stacks, then aborting with exit code "
                f"{WATCHDOG_EXIT_CODE} so an elastic launcher (--max_restarts) "
                "can restart the group instead of hanging forever.\n"
            )
            dump_all_stacks(out)
        except Exception:  # pragma: no cover - never block the abort
            pass
        try:
            # Black-box postmortem (no-op unless ATX_POSTMORTEM_DIR is
            # set). Must run HERE: os._exit below bypasses atexit, so
            # nothing later could write it. Lazy import + guard: a dying
            # process must never die harder because the bundle hiccupped.
            from ..telemetry import flight as _flight

            _flight.dump_postmortem(
                "watchdog_114", extra={"deadline_secs": deadline}
            )
        except Exception:  # pragma: no cover - never block the abort
            pass
        if self._abort is not None:
            self._abort()
            self.fired.set()  # set AFTER the abort ran (test ordering seam)
            return
        self.fired.set()
        os._exit(WATCHDOG_EXIT_CODE)  # pragma: no cover - kills the process


_ENV_WATCHDOG: Watchdog | None = None


def watchdog_from_env() -> Watchdog | None:
    """The process-wide watchdog configured by ``ATX_WATCHDOG_SECS`` (None
    when unset/invalid/<=0). One instance per deadline value, shared by
    every train step in the process."""
    raw = os.environ.get("ATX_WATCHDOG_SECS")
    if not raw:
        return None
    try:
        deadline = float(raw)
    except ValueError:
        return None
    if deadline <= 0:
        return None
    global _ENV_WATCHDOG
    if _ENV_WATCHDOG is not None and _ENV_WATCHDOG.deadline != deadline:
        _ENV_WATCHDOG.stop()  # a reconfigured deadline must not leave the
        _ENV_WATCHDOG = None  # old armed thread behind to fire later
    if _ENV_WATCHDOG is None or _ENV_WATCHDOG.deadline != deadline:
        first_raw = os.environ.get("ATX_WATCHDOG_FIRST_STEP_SECS")
        try:
            first = float(first_raw) if first_raw else deadline * 10.0
        except ValueError:
            first = deadline * 10.0
        _ENV_WATCHDOG = Watchdog(deadline, first_deadline_secs=first)
    return _ENV_WATCHDOG

"""Process, accelerator, and gradient state singletons.

TPU-native redesign of the reference state layer
(`/root/reference/src/accelerate/state.py` — `PartialState` :123,
`AcceleratorState` :850, `GradientState` :1181). The shared-``__dict__``
singleton pattern (reference `state.py:162,178`) is kept: every instance of a
state class aliases one process-wide dict, so any module can do
``ProcessState()`` and observe the same initialized state.

What changes vs the reference:

- Backend detection + ``torch.distributed.init_process_group``
  (`state.py:226,:267,:734-799`) collapses into `jax.distributed.initialize`
  (multi-host control plane) — collectives are XLA HLO ops over ICI/DCN, so
  there is no backend zoo to manage.
- "One process per device" becomes "one process per host"; `jax.devices()` /
  `jax.local_devices()` give the global/local accelerator view.
- Device placement (`state.py:801-825`) is not a process property: arrays are
  placed by shardings on the mesh (`parallel/mesh.py`).
"""

from __future__ import annotations

import logging
import os
import random as _random
import threading
import time as _time
from contextlib import contextmanager
from typing import Any, Callable, Iterator

import jax
import numpy as np

from .parallel.mesh import Mesh, MeshConfig, build_mesh
from .utils.environment import get_int_from_env, get_str_from_env, parse_flag_from_env

logger = logging.getLogger(__name__)

_jax_distributed_initialized = False
_init_lock = threading.Lock()


def _maybe_collective_log(kind: str, name: str) -> None:
    """Opt-in runtime collective-log mirror (``ATX_COLLECTIVE_LOG=1``, see
    `analysis/collective_log.py`). One env lookup when off; never raises."""
    if os.environ.get("ATX_COLLECTIVE_LOG", "").strip().lower() not in (
        "1",
        "true",
        "yes",
        "on",
    ):
        return
    try:
        from .analysis.collective_log import runtime_record

        runtime_record(kind, name)
    except Exception:  # pragma: no cover - diagnostics must not break sync
        pass


def maybe_initialize_jax_distributed() -> None:
    """Initialize the JAX multi-host control plane if the launcher asked for it.

    The launcher (`commands/launch.py`) sets ``ATX_COORDINATOR_ADDRESS``,
    ``ATX_NUM_PROCESSES`` and ``ATX_PROCESS_ID`` in each child — the analog of
    the reference's ``MASTER_ADDR/MASTER_PORT/RANK/WORLD_SIZE`` contract
    (`utils/launch.py:98-470`). On GCE TPU pods `jax.distributed.initialize()`
    can also self-discover via instance metadata, so we call it bare when
    ``ATX_MULTIHOST=1`` without explicit coordinates.
    """
    global _jax_distributed_initialized
    with _init_lock:
        if _jax_distributed_initialized:
            return
        # The env contract must win over a latched platform config: site
        # hooks (e.g. a TPU-tunnel sitecustomize) may have set jax_platforms
        # at interpreter start, in which case a child launched with
        # JAX_PLATFORMS=cpu would silently attach the parent's TPU backend.
        env_platforms = os.environ.get("JAX_PLATFORMS")
        if env_platforms:
            try:
                from jax._src import xla_bridge as _xb

                if not _xb._backends:  # backends not yet latched
                    jax.config.update("jax_platforms", env_platforms)
            except Exception:  # pragma: no cover - private-API move
                pass
        coordinator = get_str_from_env(
            ("ATX_COORDINATOR_ADDRESS", "JAX_COORDINATOR_ADDRESS"), ""
        )
        num_processes = get_int_from_env(("ATX_NUM_PROCESSES", "JAX_NUM_PROCESSES"), 0)
        process_id = get_int_from_env(("ATX_PROCESS_ID", "JAX_PROCESS_ID"), -1)
        if coordinator and num_processes > 1:
            _initialize_distributed_with_retries(
                coordinator_address=coordinator,
                num_processes=num_processes,
                process_id=process_id if process_id >= 0 else None,
            )
            _jax_distributed_initialized = True
        elif parse_flag_from_env("ATX_MULTIHOST"):
            _initialize_distributed_with_retries()
            _jax_distributed_initialized = True


def _initialize_distributed_with_retries(**kwargs: Any) -> None:
    """`jax.distributed.initialize` with bounded exponential backoff + jitter.

    The coordination service is the flakiest moment of a pod launch: workers
    race the coordinator's bind, and a slow heartbeat at init kills the whole
    group (the failure mode behind the two flaky multi-process tests on the
    ROADMAP). Knobs:

    - ``ATX_COORD_INIT_RETRIES`` (default 3): retries *after* the first
      failure, backing off 1s → 2s → 4s … (capped at 30s) with up to +100%
      jitter so restarted workers don't re-stampede the coordinator.
    - ``ATX_COORD_TIMEOUT_SECS``: forwarded as ``initialization_timeout`` so
      a dead coordinator fails fast instead of blocking for jax's default;
      dropped transparently on jax builds without the kwarg.
    """
    retries = get_int_from_env(("ATX_COORD_INIT_RETRIES",), 3)
    timeout_secs = get_int_from_env(("ATX_COORD_TIMEOUT_SECS",), 0)
    if timeout_secs > 0:
        kwargs["initialization_timeout"] = timeout_secs
    delay = 1.0
    failures = 0
    while True:
        try:
            jax.distributed.initialize(**kwargs)
            return
        except TypeError:
            if "initialization_timeout" not in kwargs:
                raise
            kwargs.pop("initialization_timeout")  # older jax: no such kwarg
            continue
        except Exception as e:
            failures += 1
            if failures > retries:
                raise
            sleep_for = delay * (1.0 + _random.random())
            logger.warning(
                "jax.distributed.initialize failed (attempt %d/%d): %s — "
                "retrying in %.1fs",
                failures,
                retries,
                e,
                sleep_for,
            )
            _time.sleep(sleep_for)
            delay = min(delay * 2.0, 30.0)


class ProcessState:
    """Singleton with information about the current process & the device world.

    Analog of the reference `PartialState` (`state.py:123`): rank helpers,
    process-ordered execution, host-side work splitting. One instance per
    *host* process (JAX SPMD: each process drives all its local devices).
    """

    _shared_state: dict[str, Any] = {}

    def __init__(self, **kwargs: Any) -> None:
        self.__dict__ = self._shared_state
        if self.initialized:
            return
        maybe_initialize_jax_distributed()
        self.debug = parse_flag_from_env("ATX_DEBUG_MODE")
        self.process_index = jax.process_index()
        self.num_processes = jax.process_count()
        self.local_devices = jax.local_devices()
        self.device_count = jax.device_count()
        self.platform = jax.devices()[0].platform
        self.device = jax.devices()[0]
        self._initialized = True

    # ------------------------------------------------------------------ basic
    @property
    def initialized(self) -> bool:
        return self.__dict__.get("_initialized", False)

    @property
    def local_device_count(self) -> int:
        return len(self.local_devices)

    @property
    def is_main_process(self) -> bool:
        return self.process_index == 0

    @property
    def is_local_main_process(self) -> bool:
        # One process per host under JAX SPMD, so every process is its host's
        # local-main. Kept as a property for API parity with the reference.
        return True

    @property
    def is_last_process(self) -> bool:
        return self.process_index == self.num_processes - 1

    @property
    def use_distributed(self) -> bool:
        return self.num_processes > 1 or self.device_count > 1

    def __repr__(self) -> str:
        return (
            f"ProcessState(process_index={self.process_index}, "
            f"num_processes={self.num_processes}, platform={self.platform!r}, "
            f"device_count={self.device_count})"
        )

    # ------------------------------------------------------------- sync/order
    def wait_for_everyone(self) -> None:
        """Block until all processes reach this point.

        Reference `state.py:359`. Uses a named cross-process barrier via the
        JAX runtime; no-op in single-process mode.
        """
        _maybe_collective_log("barrier", "wait_for_everyone")
        if self.num_processes > 1:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices("atx_wait_for_everyone")

    def _goes_first(self, is_main: bool) -> Iterator[None]:
        if not is_main:
            self.wait_for_everyone()
        yield
        if is_main:
            self.wait_for_everyone()

    @contextmanager
    def main_process_first(self) -> Iterator[None]:
        yield from self._goes_first(self.is_main_process)

    @contextmanager
    def local_main_process_first(self) -> Iterator[None]:
        yield from self._goes_first(self.is_local_main_process)

    def on_main_process(self, function: Callable) -> Callable:
        """Decorator: run only on the main process (reference `state.py:537`)."""

        def wrapper(*args: Any, **kwargs: Any) -> Any:
            if self.is_main_process:
                return function(*args, **kwargs)
            return None

        return wrapper

    def on_local_main_process(self, function: Callable) -> Callable:
        def wrapper(*args: Any, **kwargs: Any) -> Any:
            if self.is_local_main_process:
                return function(*args, **kwargs)
            return None

        return wrapper

    def on_last_process(self, function: Callable) -> Callable:
        def wrapper(*args: Any, **kwargs: Any) -> Any:
            if self.is_last_process:
                return function(*args, **kwargs)
            return None

        return wrapper

    def on_process(self, function: Callable, process_index: int) -> Callable:
        def wrapper(*args: Any, **kwargs: Any) -> Any:
            if self.process_index == process_index:
                return function(*args, **kwargs)
            return None

        return wrapper

    # ------------------------------------------------------------- splitting
    @contextmanager
    def split_between_processes(
        self, inputs: Any, apply_padding: bool = False
    ) -> Iterator[Any]:
        """Split ``inputs`` (list/tuple/dict/np.ndarray/str) across processes.

        Host-side work partitioning for uneven inputs — reference
        `state.py:407-495`. With ``apply_padding`` the last element is
        repeated so every process gets the same count (pair with
        `gather_for_metrics(..., use_gather_object=True)` style dedup).
        """
        if self.num_processes == 1:
            yield inputs
            return

        if isinstance(inputs, dict):
            split: dict[Any, Any] = {}
            length = None
            for key, value in inputs.items():
                if length is None:
                    length = len(value)
                elif len(value) != length:
                    raise ValueError(
                        "All dict values must have the same length to be split"
                    )
            for key, value in inputs.items():
                with self.split_between_processes(value, apply_padding) as v:
                    split[key] = v
            yield split
            return

        length = len(inputs)
        num_per_process = length // self.num_processes
        remainder = length % self.num_processes
        # First `remainder` processes get one extra element.
        start = num_per_process * self.process_index + min(self.process_index, remainder)
        extra = 1 if self.process_index < remainder else 0
        end = start + num_per_process + extra

        chunk = inputs[start:end]
        if apply_padding and remainder != 0:
            target = num_per_process + 1
            if isinstance(chunk, np.ndarray):
                if len(chunk) == 0 and length:
                    chunk = inputs[-1:]
                while 0 < len(chunk) < target:
                    chunk = np.concatenate([chunk, chunk[-1:]])
            elif isinstance(chunk, (list, tuple)):
                pad = list(chunk)
                fill = pad[-1] if pad else (inputs[-1] if length else None)
                while len(pad) < target:
                    pad.append(fill)
                chunk = type(chunk)(pad) if isinstance(chunk, tuple) else pad
        yield chunk

    def print(self, *args: Any, **kwargs: Any) -> None:
        if self.is_main_process:
            print(*args, **kwargs)

    # ---------------------------------------------------------------- control
    @classmethod
    def _reset_state(cls) -> None:
        """Clear the singleton (test isolation — reference `state.py:1175`)."""
        cls._shared_state.clear()

    def destroy_process_group(self) -> None:
        """Shut down the multi-host control plane (end-of-program)."""
        global _jax_distributed_initialized
        if _jax_distributed_initialized:
            jax.distributed.shutdown()
            _jax_distributed_initialized = False


class AcceleratorState:
    """Singleton adding mesh + precision + strategy config on top of ProcessState.

    Analog of reference `AcceleratorState` (`state.py:850`), minus the
    per-backend special cases: here the entire "which parallelism" question is
    answered by the mesh shape and the sharding strategy
    (`parallel/sharding.py`), not a DistributedType ladder.
    """

    _shared_state: dict[str, Any] = {}

    def __init__(
        self,
        mesh_config: MeshConfig | None = None,
        mixed_precision: str | None = None,
        **kwargs: Any,
    ) -> None:
        self.__dict__ = self._shared_state
        self.process_state = ProcessState()
        if self.initialized:
            if mesh_config is not None or mixed_precision is not None:
                logger.warning(
                    "AcceleratorState is already initialized; the mesh_config/"
                    "mixed_precision arguments passed now are ignored. Call "
                    "AcceleratorState._reset_state() first to reconfigure."
                )
            return
        self.mixed_precision = mixed_precision or os.environ.get(
            "ATX_MIXED_PRECISION", "no"
        )
        # Launcher env contract fallback (ATX_MESH_*), mirroring the reference
        # plugins' ACCELERATE_* __post_init__ reads.
        self._mesh_config = mesh_config if mesh_config is not None else MeshConfig.from_env()
        self._mesh: Mesh | None = None
        self._initialized = True

    @property
    def initialized(self) -> bool:
        return self.__dict__.get("_initialized", False)

    @property
    def mesh(self) -> Mesh:
        if self._mesh is None:
            self._mesh = build_mesh(self._mesh_config)
        return self._mesh

    def set_mesh(self, mesh: Mesh) -> None:
        self._mesh = mesh

    # Pass-through process helpers so AcceleratorState is a superset.
    def __getattr__(self, name: str) -> Any:
        # Called only when normal lookup fails; delegate to ProcessState.
        ps = self.__dict__.get("process_state")
        if ps is not None and hasattr(ps, name):
            return getattr(ps, name)
        raise AttributeError(name)

    @classmethod
    def _reset_state(cls, reset_partial_state: bool = False) -> None:
        cls._shared_state.clear()
        if reset_partial_state:
            ProcessState._reset_state()


class GradientState:
    """Singleton tracking gradient accumulation & dataloader-edge information.

    Analog of reference `GradientState` (`state.py:1181-1322`). In the TPU
    design gradient accumulation happens *inside* the jitted train step
    (microbatch `lax.scan`), so ``sync_gradients`` is True at every outer
    step; the fields remain because the data pipeline uses this object to
    advertise `end_of_dataloader` / `remainder` for metric-correct gathering
    (`gather_for_metrics`, reference `accelerator.py:2645-2668`).
    """

    _shared_state: dict[str, Any] = {}

    def __init__(self, gradient_accumulation_steps: int | None = None) -> None:
        self.__dict__ = self._shared_state
        if not self.initialized:
            self.sync_gradients = True
            self.num_steps = 1
            self.active_dataloader = None
            self.dataloader_references: list[Any] = [None]
        if gradient_accumulation_steps is not None:
            self.num_steps = gradient_accumulation_steps

    @property
    def initialized(self) -> bool:
        return self.__dict__.get("num_steps", None) is not None

    @property
    def end_of_dataloader(self) -> bool:
        if not self.in_dataloader:
            return False
        return self.active_dataloader.end_of_dataloader

    @property
    def remainder(self) -> int:
        if not self.in_dataloader:
            return -1
        return self.active_dataloader.remainder

    @property
    def in_dataloader(self) -> bool:
        return self.active_dataloader is not None

    def _add_dataloader(self, dataloader: Any) -> None:
        self.dataloader_references.append(dataloader)
        self.active_dataloader = dataloader

    def _remove_dataloader(self, dataloader: Any) -> None:
        if dataloader in self.dataloader_references:
            self.dataloader_references.remove(dataloader)
        self.active_dataloader = self.dataloader_references[-1]

    def __repr__(self) -> str:
        return (
            f"GradientState(num_steps={self.num_steps}, "
            f"sync_gradients={self.sync_gradients}, "
            f"in_dataloader={self.in_dataloader})"
        )

    @classmethod
    def _reset_state(cls) -> None:
        cls._shared_state.clear()


def is_initialized() -> bool:
    return AcceleratorState._shared_state.get("_initialized", False)

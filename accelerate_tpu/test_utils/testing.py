"""Capability-gated test decorators + test-case helpers.

The reference ships ~50 ``require_*`` skip decorators and singleton-resetting
test cases in its package (`test_utils/testing.py:146-541`, `:595-606`) so
downstream projects can gate their own distributed tests. The TPU build's
capability matrix is smaller — platform, device count, toolchain, optional
SaaS deps, slow-test opt-in — but the pattern is the same: decorate, don't
mock.

All decorators work on test functions and classes (pytest collects the skip
either way).
"""

from __future__ import annotations

import importlib.util
import os
import unittest
from typing import Any, Callable

import jax


def _skip_unless(condition: bool, reason: str) -> Callable:
    def decorate(obj: Any) -> Any:
        obj = unittest.skipUnless(condition, reason)(obj)
        if isinstance(obj, type) and not issubclass(obj, unittest.TestCase):
            # unittest's class skip is only honored by pytest for TestCase
            # subclasses; plain pytest-style classes need a pytestmark.
            try:
                import pytest

                marks = list(getattr(obj, "pytestmark", []))
                marks.append(pytest.mark.skipif(not condition, reason=reason))
                obj.pytestmark = marks
            except ImportError:  # pragma: no cover - pytest is baked in
                pass
        return obj

    return decorate


def device_count() -> int:
    return len(jax.devices())


def on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu" or "TPU" in getattr(
            jax.devices()[0], "device_kind", ""
        )
    except Exception:
        return False


def require_tpu(test: Any) -> Any:
    """Needs a real TPU chip (the CPU-simulated mesh does not count)."""
    return _skip_unless(on_tpu(), "test requires a TPU device")(test)


def require_cpu(test: Any) -> Any:
    """Needs the CPU platform (e.g. asserts about host-simulated meshes)."""
    return _skip_unless(jax.devices()[0].platform == "cpu", "test requires CPU platform")(test)


def require_multi_device(test: Any) -> Any:
    """Needs >= 2 local devices (real or --xla_force_host_platform_device_count)."""
    return _skip_unless(device_count() >= 2, "test requires multiple devices")(test)


def require_devices(n: int) -> Callable:
    """Needs at least ``n`` local devices."""

    def decorator(test: Any) -> Any:
        return _skip_unless(device_count() >= n, f"test requires >= {n} devices")(test)

    return decorator


def require_multi_process(test: Any) -> Any:
    """Needs a multi-process (multi-host style) run."""
    return _skip_unless(jax.process_count() > 1, "test requires multiple processes")(test)


def require_native_toolchain(test: Any) -> Any:
    """Needs the C++ host kernels (`accelerate_tpu.native`) to build/load."""
    from .. import native

    return _skip_unless(native.native_available(), f"no native toolchain: {native.native_error()}")(test)


def _has_module(name: str) -> bool:
    return importlib.util.find_spec(name) is not None


def require_tensorboard(test: Any) -> Any:
    return _skip_unless(_has_module("tensorboardX") or _has_module("tensorboard"),
                        "test requires tensorboard")(test)


def require_wandb(test: Any) -> Any:
    return _skip_unless(_has_module("wandb"), "test requires wandb")(test)


def slow(test: Any) -> Any:
    """Opt-in long tests: run only with ATX_RUN_SLOW=1 (reference `slow`,
    `testing.py:146`)."""
    return _skip_unless(
        os.environ.get("ATX_RUN_SLOW", "") not in ("", "0", "false"),
        "slow test: set ATX_RUN_SLOW=1 to run",
    )(test)


class AccelerateTestCase(unittest.TestCase):
    """Resets the process-wide singletons between tests so one test's
    Accelerator/mesh cannot leak into the next (reference
    `AccelerateTestCase`, `testing.py:595-606`)."""

    def tearDown(self) -> None:
        from ..state import AcceleratorState, GradientState, ProcessState

        AcceleratorState._reset_state()
        GradientState._reset_state()
        ProcessState._reset_state()
        super().tearDown()


def are_same_tensors(a: Any, b: Any, *, atol: float = 1e-6) -> bool:
    """Cross-pytree allclose (reference `are_the_same_tensors`,
    `testing.py:641`)."""
    import numpy as np

    leaves_a, treedef_a = jax.tree_util.tree_flatten(a)
    leaves_b, treedef_b = jax.tree_util.tree_flatten(b)
    if treedef_a != treedef_b or len(leaves_a) != len(leaves_b):
        return False
    return all(
        np.allclose(np.asarray(x, np.float32), np.asarray(y, np.float32), atol=atol)
        for x, y in zip(leaves_a, leaves_b)
    )

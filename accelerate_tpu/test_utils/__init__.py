"""Shipped test utilities (reference `test_utils/`, 5,156 LoC: the bundled
self-diagnostic + tiny fixtures pattern, SURVEY.md §2.6/§4)."""

from . import faults
from .testing import (
    AccelerateTestCase,
    are_same_tensors,
    require_cpu,
    require_devices,
    require_multi_device,
    require_multi_process,
    require_native_toolchain,
    require_tensorboard,
    require_tpu,
    require_wandb,
    slow,
)
from .training import RegressionDataset, regression_init, regression_loss

__all__ = [
    "AccelerateTestCase",
    "RegressionDataset",
    "faults",
    "are_same_tensors",
    "regression_init",
    "regression_loss",
    "require_cpu",
    "require_devices",
    "require_multi_device",
    "require_multi_process",
    "require_native_toolchain",
    "require_tensorboard",
    "require_tpu",
    "require_wandb",
    "slow",
]

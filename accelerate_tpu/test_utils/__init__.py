"""Shipped test utilities (reference `test_utils/`, 5,156 LoC: the bundled
self-diagnostic + tiny fixtures pattern, SURVEY.md §2.6/§4)."""

from .training import RegressionDataset, regression_init, regression_loss

__all__ = ["RegressionDataset", "regression_init", "regression_loss"]

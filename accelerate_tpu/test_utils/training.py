"""Tiny fixtures for self-diagnostics and tests.

Reference pattern: `test_utils/training.py:22-63` — a one-parameter
`RegressionModel` + synthetic `RegressionDataset`; distributed correctness is
asserted by training it under different topologies and comparing weights.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np


class RegressionDataset:
    """y = 2x + 1 with gaussian noise; sized + indexable."""

    def __init__(self, length: int = 96, seed: int = 42) -> None:
        rng = np.random.default_rng(seed)
        self.x = rng.normal(size=(length,)).astype(np.float32)
        self.y = (2.0 * self.x + 1.0 + 0.05 * rng.normal(size=(length,))).astype(
            np.float32
        )

    def __len__(self) -> int:
        return len(self.x)

    def __getitem__(self, i: int) -> dict[str, np.ndarray]:
        return {"x": self.x[i], "y": self.y[i]}


def regression_init(rng: jax.Array) -> dict[str, jax.Array]:
    ka, kb = jax.random.split(rng)
    return {
        "a": jax.random.normal(ka, ()).astype(jnp.float32),
        "b": jax.random.normal(kb, ()).astype(jnp.float32),
    }


def regression_loss(params: dict[str, jax.Array], batch: Any, rng: Any = None) -> jax.Array:
    pred = params["a"] * batch["x"] + params["b"]
    return jnp.mean(jnp.square(pred - batch["y"]))

"""The bundled self-diagnostic driver script.

Run by `accelerate-tpu test` (reference `commands/test.py:44` runs
`test_utils/scripts/test_script.py`, 901 LoC). Exercises, under whatever
topology the launcher configured: process init, collectives, dataloader
sharding, the single-vs-distributed training-equivalence oracle (reference
`training_check`, `test_utils/scripts/test_script.py:454`), and a checkpoint
round trip. Exits non-zero on any failure.
"""

from __future__ import annotations

import sys
import tempfile

import jax
import jax.numpy as jnp
import numpy as np
import optax


def check(name: str, fn) -> bool:
    try:
        fn()
    except Exception as e:  # noqa: BLE001 - diagnostic surface
        print(f"  FAIL {name}: {type(e).__name__}: {e}")
        return False
    print(f"  ok   {name}")
    return True


def main() -> int:
    import accelerate_tpu as atx
    from accelerate_tpu.ops import collectives as ops
    from accelerate_tpu.test_utils.training import (
        RegressionDataset,
        regression_init,
        regression_loss,
    )

    acc = atx.Accelerator()
    acc.print(f"Diagnostic on {acc!r}")
    acc.print(f"  devices={jax.device_count()} processes={acc.num_processes}")
    results = []

    def init_check():
        assert acc.mesh.size == jax.device_count()
        assert acc.process_index < acc.num_processes

    results.append(check("initialization", init_check))

    def collective_check():
        x = jnp.full((4,), float(acc.process_index + 1))
        g = ops.gather({"x": x})["x"]
        assert g.shape[0] == 4 * max(acc.num_processes, 1)
        r = ops.reduce({"x": x}, "sum")["x"]
        assert np.allclose(np.asarray(r)[0], sum(range(1, acc.num_processes + 1)))

    results.append(check("collectives (gather/reduce)", collective_check))

    def dataloader_check():
        data = RegressionDataset(64)
        dl = acc.prepare_data_loader(data, batch_size=4, shuffle=True, seed=0)
        batches = list(dl)
        assert len(batches) == len(dl)
        sizes = {int(b["x"].shape[0]) for b in batches}
        assert sizes == {dl.total_batch_size}

    results.append(check("dataloader sharding", dataloader_check))

    def training_equivalence():
        # Single-device oracle
        tx = optax.sgd(0.05)
        params0 = regression_init(jax.random.PRNGKey(0))
        data = RegressionDataset(64)
        xs = np.stack([d["x"] for d in data])
        ys = np.stack([d["y"] for d in data])

        def host_train(params):
            for i in range(0, 64, 16):
                batch = {"x": jnp.asarray(xs[i : i + 16]), "y": jnp.asarray(ys[i : i + 16])}
                g = jax.grad(regression_loss)(params, batch)
                params = jax.tree.map(lambda p, gg: p - 0.05 * gg, params, g)
            return params

        expected = host_train(params0)

        state = acc.create_train_state(regression_init, tx)
        step = acc.make_train_step(regression_loss)
        dl = acc.prepare_data_loader(data, batch_size=16 // max(acc.data_parallel_size, 1))
        if 16 % max(acc.data_parallel_size, 1) != 0:
            return  # topology cannot express the oracle batch; skip
        for batch in dl:
            state, _ = step(state, batch)
        got = jax.device_get(state.params)
        for key in ("a", "b"):
            np.testing.assert_allclose(
                np.asarray(got[key]), np.asarray(expected[key]), atol=1e-4
            )

    results.append(check("training equivalence (distributed == single)", training_equivalence))

    def checkpoint_round_trip():
        state = acc.create_train_state(regression_init, optax.adam(1e-2))
        with tempfile.TemporaryDirectory() as d:
            acc.save_state(d, state)
            restored = acc.load_state(d, state)
            np.testing.assert_allclose(
                np.asarray(jax.device_get(restored.params["a"])),
                np.asarray(jax.device_get(state.params["a"])),
            )

    results.append(check("checkpoint round trip", checkpoint_round_trip))

    if all(results):
        acc.print("All diagnostics passed.")
        return 0
    acc.print(f"{results.count(False)} diagnostic(s) FAILED.")
    return 1


if __name__ == "__main__":
    sys.exit(main())

"""Fault-injection harness for the resilience tests (docs/fault_tolerance.md).

Two families of faults:

- **Byte corruption** of files already on disk — `truncate_file` (a write
  that died mid-stream), `flip_bit` (silent media/DMA corruption). The
  manifest verification in `resilience/commit.py` must catch both before
  `load_state(resume="latest")` trusts a byte.
- **Crash points** — named hooks compiled into the save/commit/offload
  paths and the serving replica loop (`resilience.commit.fault_point`),
  normally a no-op. Setting ``ATX_FAULT_KILL_AT=<point>`` makes the
  process ``os._exit(137)`` there (the kill -9 analog: no atexit, no
  flush, no cleanup); ``ATX_FAULT_RAISE_AT=<point>`` raises
  `FaultInjected` instead, for in-process tests (e.g. the delayed-rename
  scenario, or killing ONE router replica thread without taking the
  process); ``ATX_FAULT_HANG_AT=<point>`` parks the calling thread
  forever — the wedged-collective analog the per-replica watchdog must
  convert into a quarantine; ``ATX_FAULT_DELAY_AT=<point>`` sleeps
  ``ATX_FAULT_DELAY_SECS`` (default 1.0) there and continues — the
  slow-transport analog, for testing watchdog interaction, replication
  drain deadlines, and kill-during-upload races deterministically;
  ``ATX_FAULT_NAN_AT=<point>[@N]`` makes `maybe_poison(point, arr)` return
  the array with a NaN planted — the divergent-batch analog driving the
  ``ATX_NAN_GUARD`` tests (the training scripts call it on each batch).

Any spec may carry a hit count, ``<point>@N``: the fault fires on the
Nth time execution reaches that point (process-wide counter) and never
again — e.g. ``ATX_FAULT_RAISE_AT=router.replica0.step@5`` kills replica
0 mid-decode, after it has already streamed tokens. Tests that reuse a
counted spec in-process must call `_reset_counters()` between runs.

Instrumented points:

==============================  =================================================
``save.files_written``          all of this process's checkpoint files are on
                                disk, manifest NOT yet written
``save.manifest_written``       manifest written, commit NOT yet started
``commit.before_rename``        tmp dir complete, final rename NOT done
                                (the "delayed rename" fault)
``commit.before_marker``        renamed to final, ``COMMIT`` marker NOT written
``disk.after_sentinel``         disk-offload dirty sentinel written, moments
                                NOT yet mutated/flushed
``router.replica<i>.step``      router replica ``i``'s loop, after inbox
                                messages are applied, BEFORE the engine step
                                (`serving/router.py` failover injection)
``replicate.part_uploaded``     one checkpoint part landed in the object
                                store, next part NOT yet sent
                                (`resilience/replicate.py` — combine with
                                ``@N`` to die after exactly N parts)
``replicate.before_marker``     every part + manifest uploaded, remote
                                ``COMMIT`` marker NOT yet written (the
                                remote durability boundary)
``restore.peer_shard_fetched``  one peer shard file fetched + verified into
                                the local checkpoint dir, next NOT yet
                                (`checkpointing._ensure_shard_coverage`)
``shrink.agreement_proposed``   this process's topology proposal is written
                                to the agreement surface, decision NOT yet
                                reached (`resilience/elastic.py`)
``shrink.before_reshard``       topology decision adopted, live state NOT
                                yet mutated — a fault here must degrade to
                                the exit-75 relaunch path with the prior
                                committed checkpoint intact
``shrink.peer_slice_fetched``   one shard byte-range fetched from the
                                replicate store during an in-memory
                                reshard, next NOT yet
                                (`checkpointing.StoreShardSource`)
==============================  =================================================
"""

from __future__ import annotations

import os
import sys
import time
from contextlib import contextmanager
from typing import Iterator

from ..utils.environment import patch_environment

KILL_EXIT_CODE = 137  # what a real `kill -9` reports (128 + SIGKILL)

KILL_AT_ENV = "ATX_FAULT_KILL_AT"
RAISE_AT_ENV = "ATX_FAULT_RAISE_AT"
HANG_AT_ENV = "ATX_FAULT_HANG_AT"
DELAY_AT_ENV = "ATX_FAULT_DELAY_AT"
DELAY_SECS_ENV = "ATX_FAULT_DELAY_SECS"
NAN_AT_ENV = "ATX_FAULT_NAN_AT"

# Hits seen per counted spec ("point@N"); plain specs never touch this.
_HIT_COUNTS: dict[str, int] = {}


class FaultInjected(RuntimeError):
    """Raised at a crash point when ``ATX_FAULT_RAISE_AT`` names it."""


def _reset_counters() -> None:
    """Forget ``point@N`` hit counts (in-process tests reusing a spec)."""
    _HIT_COUNTS.clear()


def _should_fire(spec: str | None, name: str) -> bool:
    """Does ``spec`` (``"point"`` or ``"point@N"``) fire at this visit of
    ``name``? Counted specs fire exactly on the Nth visit."""
    if spec is None:
        return False
    if spec == name:
        return True
    if spec.startswith(name + "@"):
        try:
            n = int(spec.rsplit("@", 1)[1])
        except ValueError:
            return False
        _HIT_COUNTS[spec] = _HIT_COUNTS.get(spec, 0) + 1
        return _HIT_COUNTS[spec] == n
    return False


def crash_point(name: str) -> None:
    """The hook body `resilience.commit.fault_point` dispatches to once a
    fault env var is present."""
    if _should_fire(os.environ.get(DELAY_AT_ENV), name):
        try:
            delay = float(os.environ.get(DELAY_SECS_ENV, "") or 1.0)
        except ValueError:
            delay = 1.0
        sys.stderr.write(
            f"[faults] injecting {delay:.3g}s latency at crash point {name!r}\n"
        )
        sys.stderr.flush()
        time.sleep(delay)
        # fall through: a delay composes with the other fault families
    if _should_fire(os.environ.get(RAISE_AT_ENV), name):
        raise FaultInjected(f"injected fault at crash point {name!r}")
    if _should_fire(os.environ.get(HANG_AT_ENV), name):
        sys.stderr.write(f"[faults] wedge analog at crash point {name!r}\n")
        sys.stderr.flush()
        while True:  # park this thread forever — only a watchdog sees it
            time.sleep(3600)
    if _should_fire(os.environ.get(KILL_AT_ENV), name):
        sys.stderr.write(f"[faults] kill -9 analog at crash point {name!r}\n")
        sys.stderr.flush()
        os._exit(KILL_EXIT_CODE)


def maybe_poison(name: str, array):
    """Numeric fault: when ``ATX_FAULT_NAN_AT=<name>[@N]`` names this point,
    return ``array`` with its first element set to NaN — the divergent-batch
    analog the ``ATX_NAN_GUARD`` budget exists for. ``name@N`` poisons only
    the Nth visit (process-wide counter, same as the crash-point specs).
    Returns the array unchanged otherwise."""
    if not _should_fire(os.environ.get(NAN_AT_ENV), name):
        return array
    sys.stderr.write(f"[faults] NaN poison at point {name!r}\n")
    sys.stderr.flush()
    import numpy as np

    out = np.array(array, copy=True)
    out.reshape(-1)[0] = np.nan
    return out


@contextmanager
def raise_at(point: str) -> Iterator[None]:
    """In-process fault: `FaultInjected` is raised when execution reaches
    ``point`` inside the block."""
    with patch_environment(**{RAISE_AT_ENV: point}):
        yield


@contextmanager
def delay_at(point: str, secs: float = 1.0) -> Iterator[None]:
    """In-process latency fault: execution sleeps ``secs`` each time it
    reaches ``point`` inside the block (``point@N`` delays only the Nth
    hit)."""
    with patch_environment(
        **{DELAY_AT_ENV: point, DELAY_SECS_ENV: repr(secs)}
    ):
        yield


def kill_env(point: str, base: dict | None = None) -> dict:
    """Env dict for a subprocess that should die (``os._exit(137)``) at
    ``point`` — the deterministic kill-during-save harness."""
    env = dict(os.environ if base is None else base)
    env[KILL_AT_ENV] = point
    return env


def truncate_file(path: str, keep_fraction: float = 0.5) -> int:
    """Truncate ``path`` to a fraction of its size (a write that died
    mid-stream). Returns the new size."""
    size = os.path.getsize(path)
    keep = max(0, int(size * keep_fraction))
    with open(path, "r+b") as f:
        f.truncate(keep)
    return keep


def flip_bit(path: str, byte_offset: int | None = None, bit: int = 0) -> int:
    """Flip one bit in ``path`` (default: the middle byte) — silent
    corruption that leaves size intact, so only a checksum catches it.
    Returns the byte offset flipped."""
    size = os.path.getsize(path)
    if size == 0:
        raise ValueError(f"cannot flip a bit in empty file {path}")
    offset = size // 2 if byte_offset is None else byte_offset
    with open(path, "r+b") as f:
        f.seek(offset)
        byte = f.read(1)
        f.seek(offset)
        f.write(bytes([byte[0] ^ (1 << bit)]))
    return offset

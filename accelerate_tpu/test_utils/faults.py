"""Fault-injection harness for the resilience tests (docs/fault_tolerance.md).

Two families of faults:

- **Byte corruption** of files already on disk — `truncate_file` (a write
  that died mid-stream), `flip_bit` (silent media/DMA corruption). The
  manifest verification in `resilience/commit.py` must catch both before
  `load_state(resume="latest")` trusts a byte.
- **Crash points** — named hooks compiled into the save/commit/offload
  paths and the serving replica loop (`resilience.commit.fault_point`),
  normally a no-op. Setting ``ATX_FAULT_KILL_AT=<point>`` makes the
  process ``os._exit(137)`` there (the kill -9 analog: no atexit, no
  flush, no cleanup); ``ATX_FAULT_RAISE_AT=<point>`` raises
  `FaultInjected` instead, for in-process tests (e.g. the delayed-rename
  scenario, or killing ONE router replica thread without taking the
  process); ``ATX_FAULT_HANG_AT=<point>`` parks the calling thread
  forever — the wedged-collective analog the per-replica watchdog must
  convert into a quarantine; ``ATX_FAULT_DELAY_AT=<point>`` sleeps
  ``ATX_FAULT_DELAY_SECS`` (default 1.0) there and continues — the
  slow-transport analog, for testing watchdog interaction, replication
  drain deadlines, and kill-during-upload races deterministically;
  ``ATX_FAULT_NAN_AT=<point>[@N]`` makes `maybe_poison(point, arr)` return
  the array with a NaN planted — the divergent-batch analog driving the
  ``ATX_NAN_GUARD`` tests (the training scripts call it on each batch).

Any spec may carry a hit count, ``<point>@N``: the fault fires on the
Nth time execution reaches that point (process-wide counter) and never
again — e.g. ``ATX_FAULT_RAISE_AT=router.replica0.step@5`` kills replica
0 mid-decode, after it has already streamed tokens. Tests that reuse a
counted spec in-process must call `_reset_counters()` between runs.

Instrumented points:

==============================  =================================================
``save.files_written``          all of this process's checkpoint files are on
                                disk, manifest NOT yet written
``save.manifest_written``       manifest written, commit NOT yet started
``commit.before_rename``        tmp dir complete, final rename NOT done
                                (the "delayed rename" fault)
``commit.before_marker``        renamed to final, ``COMMIT`` marker NOT written
``disk.after_sentinel``         disk-offload dirty sentinel written, moments
                                NOT yet mutated/flushed
``router.replica<i>.step``      router replica ``i``'s loop, after inbox
                                messages are applied, BEFORE the engine step
                                (`serving/router.py` failover injection)
``replicate.part_uploaded``     one checkpoint part landed in the object
                                store, next part NOT yet sent
                                (`resilience/replicate.py` — combine with
                                ``@N`` to die after exactly N parts)
``replicate.before_marker``     every part + manifest uploaded, remote
                                ``COMMIT`` marker NOT yet written (the
                                remote durability boundary)
``restore.peer_shard_fetched``  one peer shard file fetched + verified into
                                the local checkpoint dir, next NOT yet
                                (`checkpointing._ensure_shard_coverage`)
``shrink.agreement_proposed``   this process's topology proposal is written
                                to the agreement surface, decision NOT yet
                                reached (`resilience/elastic.py`)
``shrink.before_reshard``       topology decision adopted, live state NOT
                                yet mutated — a fault here must degrade to
                                the exit-75 relaunch path with the prior
                                committed checkpoint intact
``shrink.peer_slice_fetched``   one shard byte-range fetched from the
                                replicate store during an in-memory
                                reshard, next NOT yet
                                (`checkpointing.StoreShardSource`)
``engine.step``                 serving engine scheduler step, BEFORE
                                admission/prefill/decode dispatch
                                (`serving/engine.py` — the engine-level
                                chaos injection point)
==============================  =================================================

For randomized campaigns (`atx chaos`), :class:`FaultSchedule` samples a
seeded fault assignment over `active_points()` — probability-per-point, at
most one point per fault kind (each ``ATX_FAULT_*_AT`` env var holds one
spec) — and renders it as the env dict the existing ``<point>@N`` machinery
consumes, so a campaign episode is replayable from its seed alone
(``ATX_FAULT_SEED`` names the default seed).
"""

from __future__ import annotations

import os
import random
import sys
import time
from contextlib import contextmanager
from typing import Iterator, Sequence

from ..utils.environment import patch_environment

KILL_EXIT_CODE = 137  # what a real `kill -9` reports (128 + SIGKILL)

KILL_AT_ENV = "ATX_FAULT_KILL_AT"
RAISE_AT_ENV = "ATX_FAULT_RAISE_AT"
HANG_AT_ENV = "ATX_FAULT_HANG_AT"
DELAY_AT_ENV = "ATX_FAULT_DELAY_AT"
DELAY_SECS_ENV = "ATX_FAULT_DELAY_SECS"
NAN_AT_ENV = "ATX_FAULT_NAN_AT"
FAULT_SEED_ENV = "ATX_FAULT_SEED"

# Fault kind -> the env var its spec lives in. Each var holds exactly ONE
# spec, so a FaultSchedule assigns at most one point per kind.
FAULT_KIND_ENVS: dict[str, str] = {
    "raise": RAISE_AT_ENV,
    "hang": HANG_AT_ENV,
    "delay": DELAY_AT_ENV,
    "kill": KILL_AT_ENV,
}

# The static catalog of instrumented crash points (the docstring table).
# Parameterized points list their concrete everyday instances.
_KNOWN_POINTS: tuple[str, ...] = (
    "save.files_written",
    "save.manifest_written",
    "commit.before_rename",
    "commit.before_marker",
    "disk.after_sentinel",
    "router.replica0.step",
    "router.replica1.step",
    "engine.step",
    "replicate.part_uploaded",
    "replicate.before_marker",
    "restore.peer_shard_fetched",
    "shrink.agreement_proposed",
    "shrink.before_reshard",
    "shrink.peer_slice_fetched",
)

# Points seen live by `crash_point` this process (covers dynamically named
# instances, e.g. router.replica7.step in a wide fleet).
_SEEN_POINTS: set[str] = set()


def active_points(prefix: str | None = None) -> tuple[str, ...]:
    """Every injectable crash point known to this process: the static
    catalog plus any dynamically named instance `crash_point` has actually
    visited. ``prefix`` filters (e.g. ``"router."`` for the campaign driver
    to scope a schedule to one subsystem)."""
    points = sorted(set(_KNOWN_POINTS) | _SEEN_POINTS)
    if prefix is not None:
        points = [p for p in points if p.startswith(prefix)]
    return tuple(points)

# Hits seen per counted spec ("point@N"); plain specs never touch this.
_HIT_COUNTS: dict[str, int] = {}


class FaultInjected(RuntimeError):
    """Raised at a crash point when ``ATX_FAULT_RAISE_AT`` names it."""


def _reset_counters() -> None:
    """Forget ``point@N`` hit counts (in-process tests reusing a spec)."""
    _HIT_COUNTS.clear()


def _should_fire(spec: str | None, name: str) -> bool:
    """Does ``spec`` (``"point"`` or ``"point@N"``) fire at this visit of
    ``name``? Counted specs fire exactly on the Nth visit."""
    if spec is None:
        return False
    if spec == name:
        return True
    if spec.startswith(name + "@"):
        try:
            n = int(spec.rsplit("@", 1)[1])
        except ValueError:
            return False
        _HIT_COUNTS[spec] = _HIT_COUNTS.get(spec, 0) + 1
        return _HIT_COUNTS[spec] == n
    return False


def crash_point(name: str) -> None:
    """The hook body `resilience.commit.fault_point` dispatches to once a
    fault env var is present."""
    _SEEN_POINTS.add(name)
    if _should_fire(os.environ.get(DELAY_AT_ENV), name):
        try:
            delay = float(os.environ.get(DELAY_SECS_ENV, "") or 1.0)
        except ValueError:
            delay = 1.0
        sys.stderr.write(
            f"[faults] injecting {delay:.3g}s latency at crash point {name!r}\n"
        )
        sys.stderr.flush()
        time.sleep(delay)
        # fall through: a delay composes with the other fault families
    if _should_fire(os.environ.get(RAISE_AT_ENV), name):
        raise FaultInjected(f"injected fault at crash point {name!r}")
    if _should_fire(os.environ.get(HANG_AT_ENV), name):
        sys.stderr.write(f"[faults] wedge analog at crash point {name!r}\n")
        sys.stderr.flush()
        while True:  # park this thread forever — only a watchdog sees it
            time.sleep(3600)
    if _should_fire(os.environ.get(KILL_AT_ENV), name):
        sys.stderr.write(f"[faults] kill -9 analog at crash point {name!r}\n")
        sys.stderr.flush()
        os._exit(KILL_EXIT_CODE)


def maybe_poison(name: str, array):
    """Numeric fault: when ``ATX_FAULT_NAN_AT=<name>[@N]`` names this point,
    return ``array`` with its first element set to NaN — the divergent-batch
    analog the ``ATX_NAN_GUARD`` budget exists for. ``name@N`` poisons only
    the Nth visit (process-wide counter, same as the crash-point specs).
    Returns the array unchanged otherwise."""
    if not _should_fire(os.environ.get(NAN_AT_ENV), name):
        return array
    sys.stderr.write(f"[faults] NaN poison at point {name!r}\n")
    sys.stderr.flush()
    import numpy as np

    out = np.array(array, copy=True)
    out.reshape(-1)[0] = np.nan
    return out


@contextmanager
def raise_at(point: str) -> Iterator[None]:
    """In-process fault: `FaultInjected` is raised when execution reaches
    ``point`` inside the block."""
    with patch_environment(**{RAISE_AT_ENV: point}):
        yield


@contextmanager
def delay_at(point: str, secs: float = 1.0) -> Iterator[None]:
    """In-process latency fault: execution sleeps ``secs`` each time it
    reaches ``point`` inside the block (``point@N`` delays only the Nth
    hit)."""
    with patch_environment(
        **{DELAY_AT_ENV: point, DELAY_SECS_ENV: repr(secs)}
    ):
        yield


def kill_env(point: str, base: dict | None = None) -> dict:
    """Env dict for a subprocess that should die (``os._exit(137)``) at
    ``point`` — the deterministic kill-during-save harness."""
    env = dict(os.environ if base is None else base)
    env[KILL_AT_ENV] = point
    return env


def truncate_file(path: str, keep_fraction: float = 0.5) -> int:
    """Truncate ``path`` to a fraction of its size (a write that died
    mid-stream). Returns the new size."""
    size = os.path.getsize(path)
    keep = max(0, int(size * keep_fraction))
    with open(path, "r+b") as f:
        f.truncate(keep)
    return keep


class FaultSchedule:
    """A seeded, replayable fault assignment over the crash-point registry.

    Samples — with a stdlib `random.Random(seed)` so the draw is stable
    across platforms and numpy versions — an independent
    probability-``probability`` coin per fault kind; a kind that comes up
    faulty gets one point from ``points`` and a hit count in
    ``[1, max_hits]``, rendered as the existing ``<point>@N`` counted spec.
    At most one point per kind because each ``ATX_FAULT_*_AT`` env var
    holds a single spec. The same ``(seed, points, kinds, probability,
    max_hits)`` always reproduces the same assignment — that is the chaos
    campaign's replay contract.

    ``seed=None`` reads ``ATX_FAULT_SEED`` (default 0).
    """

    def __init__(
        self,
        seed: int | None = None,
        *,
        points: Sequence[str] | None = None,
        kinds: Sequence[str] = ("raise", "delay"),
        probability: float = 0.5,
        max_hits: int = 4,
    ) -> None:
        if seed is None:
            try:
                seed = int(os.environ.get(FAULT_SEED_ENV, "") or 0)
            except ValueError:
                seed = 0
        unknown = [k for k in kinds if k not in FAULT_KIND_ENVS]
        if unknown:
            raise ValueError(
                f"unknown fault kinds {unknown}; choose from "
                f"{sorted(FAULT_KIND_ENVS)}"
            )
        self.seed = seed
        self.points = tuple(points if points is not None else active_points())
        self.kinds = tuple(kinds)
        self.probability = probability
        self.max_hits = max(1, int(max_hits))
        self.assignments: dict[str, str] = {}
        rng = random.Random(seed)
        for kind in self.kinds:
            # Draw the coin AND the would-be assignment every iteration so
            # one kind's outcome never shifts another kind's stream.
            coin = rng.random()
            point = rng.choice(self.points) if self.points else None
            hits = rng.randint(1, self.max_hits)
            if point is not None and coin < probability:
                self.assignments[kind] = f"{point}@{hits}"

    def env(self) -> dict[str, str]:
        """The env-var dict (`ATX_FAULT_<KIND>_AT` -> ``point@N``) the
        existing `crash_point` machinery consumes — hand it to
        `utils.environment.patch_environment` or a subprocess env."""
        return {FAULT_KIND_ENVS[k]: spec for k, spec in self.assignments.items()}

    def describe(self) -> dict:
        """Stable JSON-serializable description for the episode report."""
        return {
            "seed": self.seed,
            "kinds": list(self.kinds),
            "probability": self.probability,
            "assignments": dict(sorted(self.assignments.items())),
        }

    def __repr__(self) -> str:  # pragma: no cover - debugging nicety
        return f"FaultSchedule(seed={self.seed}, assignments={self.assignments})"


def flip_bit(path: str, byte_offset: int | None = None, bit: int = 0) -> int:
    """Flip one bit in ``path`` (default: the middle byte) — silent
    corruption that leaves size intact, so only a checksum catches it.
    Returns the byte offset flipped."""
    size = os.path.getsize(path)
    if size == 0:
        raise ValueError(f"cannot flip a bit in empty file {path}")
    offset = size // 2 if byte_offset is None else byte_offset
    with open(path, "r+b") as f:
        f.seek(offset)
        byte = f.read(1)
        f.seek(offset)
        f.write(bytes([byte[0] ^ (1 << bit)]))
    return offset

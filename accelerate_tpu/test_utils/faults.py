"""Fault-injection harness for the resilience tests (docs/fault_tolerance.md).

Two families of faults:

- **Byte corruption** of files already on disk — `truncate_file` (a write
  that died mid-stream), `flip_bit` (silent media/DMA corruption). The
  manifest verification in `resilience/commit.py` must catch both before
  `load_state(resume="latest")` trusts a byte.
- **Crash points** — named hooks compiled into the save/commit/offload
  paths (`resilience.commit.fault_point`), normally a no-op. Setting
  ``ATX_FAULT_KILL_AT=<point>`` makes the process ``os._exit(137)`` there
  (the kill -9 analog: no atexit, no flush, no cleanup); setting
  ``ATX_FAULT_RAISE_AT=<point>`` raises `FaultInjected` instead, for
  in-process tests (e.g. the delayed-rename scenario: a save whose tmp dir
  is fully written but never renamed).

Instrumented points:

==============================  =================================================
``save.files_written``          all of this process's checkpoint files are on
                                disk, manifest NOT yet written
``save.manifest_written``       manifest written, commit NOT yet started
``commit.before_rename``        tmp dir complete, final rename NOT done
                                (the "delayed rename" fault)
``commit.before_marker``        renamed to final, ``COMMIT`` marker NOT written
``disk.after_sentinel``         disk-offload dirty sentinel written, moments
                                NOT yet mutated/flushed
==============================  =================================================
"""

from __future__ import annotations

import os
import sys
from contextlib import contextmanager
from typing import Iterator

from ..utils.environment import patch_environment

KILL_EXIT_CODE = 137  # what a real `kill -9` reports (128 + SIGKILL)

KILL_AT_ENV = "ATX_FAULT_KILL_AT"
RAISE_AT_ENV = "ATX_FAULT_RAISE_AT"


class FaultInjected(RuntimeError):
    """Raised at a crash point when ``ATX_FAULT_RAISE_AT`` names it."""


def crash_point(name: str) -> None:
    """The hook body `resilience.commit.fault_point` dispatches to once a
    fault env var is present."""
    if os.environ.get(RAISE_AT_ENV) == name:
        raise FaultInjected(f"injected fault at crash point {name!r}")
    if os.environ.get(KILL_AT_ENV) == name:
        sys.stderr.write(f"[faults] kill -9 analog at crash point {name!r}\n")
        sys.stderr.flush()
        os._exit(KILL_EXIT_CODE)


@contextmanager
def raise_at(point: str) -> Iterator[None]:
    """In-process fault: `FaultInjected` is raised when execution reaches
    ``point`` inside the block."""
    with patch_environment(**{RAISE_AT_ENV: point}):
        yield


def kill_env(point: str, base: dict | None = None) -> dict:
    """Env dict for a subprocess that should die (``os._exit(137)``) at
    ``point`` — the deterministic kill-during-save harness."""
    env = dict(os.environ if base is None else base)
    env[KILL_AT_ENV] = point
    return env


def truncate_file(path: str, keep_fraction: float = 0.5) -> int:
    """Truncate ``path`` to a fraction of its size (a write that died
    mid-stream). Returns the new size."""
    size = os.path.getsize(path)
    keep = max(0, int(size * keep_fraction))
    with open(path, "r+b") as f:
        f.truncate(keep)
    return keep


def flip_bit(path: str, byte_offset: int | None = None, bit: int = 0) -> int:
    """Flip one bit in ``path`` (default: the middle byte) — silent
    corruption that leaves size intact, so only a checksum catches it.
    Returns the byte offset flipped."""
    size = os.path.getsize(path)
    if size == 0:
        raise ValueError(f"cannot flip a bit in empty file {path}")
    offset = size // 2 if byte_offset is None else byte_offset
    with open(path, "r+b") as f:
        f.seek(offset)
        byte = f.read(1)
        f.seek(offset)
        f.write(bytes([byte[0] ^ (1 << bit)]))
    return offset

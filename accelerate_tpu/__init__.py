"""accelerate_tpu — a TPU-native training & inference framework.

Ground-up JAX/XLA/Pallas re-design of the HuggingFace Accelerate capability
surface (reference: /root/reference, see SURVEY.md). The compute path is one
pjit-compiled train step over explicitly sharded pytrees on a
`jax.sharding.Mesh`; the runtime around it (state, launcher, data pipeline,
checkpointing, trackers) mirrors the reference's feature set.
"""

__version__ = "0.1.0"

from .accelerator import (
    Accelerator,
    DynamicLossScale,
    NonFiniteGuardError,
    TrainState,
)
from . import analysis
from .analysis import AnalysisWarning, LintError, lint_step, lint_training
from .big_modeling import (
    ShardingPlan,
    infer_sharding_plan,
    init_empty_weights,
    load_checkpoint_and_dispatch,
    offload_blocks,
    streamed_scan,
)
from .data import ArrayDataset, DataLoader, prepare_data_loader, skip_first_batches
from .generation import GenerationConfig, Generator, generate
from .speculative import SpeculativeGenerator, generate_speculative
from . import serving
from . import resilience
from . import telemetry
from .resilience import (
    PREEMPTION_EXIT_CODE,
    WATCHDOG_EXIT_CODE,
    Watchdog,
    install_preemption_handler,
    preemption_requested,
)
from .models.hf import from_hf_config, load_pretrained, save_pretrained
from .launchers import debug_launcher, notebook_launcher
from .local_sgd import (
    LocalSGD,
    make_local_sgd_step,
    stack_train_state,
    unstack_train_state,
)
from .logging import get_logger
from .parallel import MeshConfig, build_mesh
from .parallel.disk_offload import disk_offloaded_adamw
from .parallel.transfer import TransferEngine, get_transfer_engine
from .parallel.host_offload import host_offloaded_adamw
from .parallel.pipeline import Pipeline, llama_pipeline
from .parallel.sharding import ShardingStrategy
from .state import AcceleratorState, GradientState, ProcessState
from .tracking import GeneralTracker, JSONTracker, TensorBoardTracker, WandBTracker
from .utils import (
    DataLoaderConfiguration,
    DistributedType,
    FsdpPlugin,
    find_executable_batch_size,
    release_memory,
    GradientAccumulationPlugin,
    MixedPrecisionPolicy,
    ProfileKwargs,
    ProjectConfiguration,
    ShardingStrategyType,
    TensorParallelPlugin,
    set_seed,
    tqdm,
)

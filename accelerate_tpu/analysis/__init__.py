"""Ahead-of-time static analysis for jitted train/infer steps (`atx lint`).

A wrong PartitionSpec on TPU does not error — XLA silently inserts
replication or a full all-gather and the job runs 5-50x slower. Because
GSPMD derives every collective from the annotations, those mistakes are
statically checkable: this package traces a step with `jax.eval_shape` /
`jax.make_jaxpr`, inspects the lowered StableHLO and the compiled HLO, and
emits structured `Finding` records across four rule families:

- **ATX1xx sharding** — spec axes missing from the mesh, dims the mesh
  can't divide (silent padding/replication), large params left fully
  replicated, param-vs-optimizer-state spec conflicts;
- **ATX2xx donation** — train state not donated (2x HBM), donations XLA
  dropped because no output could alias the buffer;
- **ATX3xx recompilation** — unhashable/unstable static args, batch-shape
  drift across calls, dtype/weak-type flips;
- **ATX4xx host sync & collectives** — callbacks/`debug.print` in the hot
  jaxpr, and collective byte accounting mined from the compiled HLO with a
  threshold catching accidental full-param gathers;
- **ATX6xx performance** — a static roofline over the compiled HLO
  (`analysis/roofline.py`): per-chip-generation peaks bucket every op into
  MXU / vector / HBM / collective time, yielding a step-time lower bound
  and an MFU ceiling before anything runs, plus rules for exposed
  collectives, tile-padding waste, precision-fallback dots, and fusion
  breaks — the series `perf/budgets.json` ratchets (`make lint-perf`);
- **ATX7xx memory** — a static HBM *timeline* over the same compiled HLO
  (`analysis/memory.py`): scheduled-liveness sweep with donation credit,
  while-body residency, and per-category attribution, yielding the peak
  live bytes and an OOM-ahead-of-time gate vs the chip's HBM, plus rules
  for live-range waste, at-peak donation misses, and temp blowups; the
  serving capacity planner (`analysis/capacity.py`) solves max KV
  slots/paged blocks from the same arithmetic (`make lint-memory`,
  `atx estimate --serve`);
- **ATX5xx multi-host consistency** — a simulated-process replay harness
  (`host_trace.replay_host_loop`) runs a host loop once per patched
  `process_index`, records every owned collective's (op, signature, stack)
  per process, and flags the first cross-process divergence: the
  pod-hanging bug class (a SIGTERM flag checked locally, a barrier one
  rank skips, dict-ordered collective issue) caught before it reaches a
  pod. Opt-in runtime mirror: ``ATX_COLLECTIVE_LOG=1``
  (`analysis.collective_log`).

Three surfaces: `lint_step(fn, *abstract_args, mesh=...)` /
`lint_training(accelerator, ...)` / `lint_host_loop(loop_fn,
processes=N)` as a library, `Accelerator.prepare(..., lint="warn"|"error")`
inline, and the `atx lint` CLI over the `examples/` entry points
(`make lint-graph`, `make lint-multihost`). Rule catalogue:
docs/static_analysis.md.
"""

from .findings import AnalysisWarning, Finding, LintError, Report, Severity
from .engine import (
    DEFAULT_OPTIONS,
    LintContext,
    RuleSpec,
    lint_host_loop,
    lint_specs,
    lint_step,
    lint_training,
    registered_rules,
    rule,
)
from .capacity import (
    CapacityError,
    CapacityPlan,
    capacity_findings,
    check_engine_capacity,
    plan_capacity,
    plan_for_engine,
)
from .hbm import HbmBreakdown, human_bytes, state_hbm_per_device, tree_device_bytes
from .host_trace import HostEvent, HostTraceResult, replay_host_loop
from .memory import MemoryTimeline, build_timeline
from .roofline import (
    CHIP_SPECS,
    ChipSpec,
    RooflineResult,
    analyze_hlo,
    chip_spec_for,
    find_exposed_collectives,
    find_fusion_breaks,
)

# Importing the rule modules registers their rules.
from . import rules_collectives  # noqa: F401  (ATX4xx)
from . import rules_donation  # noqa: F401  (ATX2xx)
from . import rules_memory  # noqa: F401  (ATX7xx)
from . import rules_multihost  # noqa: F401  (ATX5xx)
from . import rules_perf  # noqa: F401  (ATX6xx)
from . import rules_recompile  # noqa: F401  (ATX3xx)
from . import rules_sharding  # noqa: F401  (ATX1xx)

__all__ = [
    "AnalysisWarning",
    "CapacityError",
    "CapacityPlan",
    "CHIP_SPECS",
    "ChipSpec",
    "DEFAULT_OPTIONS",
    "Finding",
    "MemoryTimeline",
    "RooflineResult",
    "analyze_hlo",
    "build_timeline",
    "capacity_findings",
    "check_engine_capacity",
    "chip_spec_for",
    "find_exposed_collectives",
    "find_fusion_breaks",
    "plan_capacity",
    "plan_for_engine",
    "HbmBreakdown",
    "HostEvent",
    "HostTraceResult",
    "LintContext",
    "LintError",
    "Report",
    "RuleSpec",
    "Severity",
    "human_bytes",
    "lint_host_loop",
    "lint_specs",
    "lint_step",
    "lint_training",
    "registered_rules",
    "replay_host_loop",
    "rule",
    "state_hbm_per_device",
    "tree_device_bytes",
]

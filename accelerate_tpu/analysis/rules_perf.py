"""ATX6xx — static performance rules over the compiled HLO roofline.

ATX1xx–5xx lint correctness; this family bounds *speed* before anything
runs. Everything derives from one `analysis/roofline.py` pass over
`LintContext.compiled_text()` against a chip-generation spec table:

- **ATX601** (info, always) — the roofline table: per-category busy time
  (MXU / vector / HBM / collective), the static step-time lower bound, the
  static MFU upper bound, and arithmetic intensity for the top-k ops. The
  full table — plus the three budget series `perf/budgets.json` ratchets
  (`static_mfu_bound`, `exposed_comms_bytes`, `padding_waste_fraction`) —
  rides in `Finding.data` for `--json` consumers.
- **ATX602** (warning) — exposed collective: an async `-start`/`-done`
  pair with too little compute scheduled between to hide the wire time.
- **ATX603** (warning) — tiling waste: a hot dot whose M/N/K dims overrun
  the native (sublane x 128) tile by a non-multiple, burning MXU FLOPs on
  padding.
- **ATX604** (warning) — precision fallback: a hot dot fed through an
  upcast convert (bf16→f32, or a quantized s8/f8 contraction lowered to a
  wide dot), running at a fraction of the narrow peak.
- **ATX605** (warning) — fusion break: an elementwise chain materialized
  to HBM between two kLoop fusions, adding a full write+read round trip
  per step.

Thresholds: the `roofline_*` / `exposed_*` / `tiling_*` /
`precision_hot_fraction` / `fusion_break_bytes` entries in
`engine.DEFAULT_OPTIONS`.
"""

from __future__ import annotations

from typing import Iterator

from .engine import LintContext, rule
from .findings import Finding, Severity
from .hbm import human_bytes
from .roofline import (
    RooflineResult,
    analyze_hlo,
    chip_spec_for,
    find_exposed_collectives,
    find_fusion_breaks,
    padded_dot_flops,
)

# Per-rule cap on emitted findings — the worst offenders tell the story;
# a 96-layer model doesn't need 96 copies of the same diagnosis.
_MAX_FINDINGS = 8


def _roofline(ctx: LintContext) -> RooflineResult | None:
    """One shared roofline pass per LintContext (cached on the ctx)."""
    cached = getattr(ctx, "_roofline_result", None)
    if cached is not None:
        return cached
    hlo = ctx.compiled_text()
    if hlo is None:
        return None
    spec = chip_spec_for(ctx.opt("roofline_chip"))
    result = analyze_hlo(hlo, spec)
    ctx._roofline_result = result
    return result


def _exposed(ctx: LintContext):
    hlo = ctx.compiled_text()
    if hlo is None:
        return []
    return find_exposed_collectives(
        hlo,
        chip_spec_for(ctx.opt("roofline_chip")),
        min_bytes=ctx.opt("exposed_min_bytes"),
        overlap_fraction=ctx.opt("exposed_overlap_fraction"),
    )


@rule(
    "ATX601",
    Severity.INFO,
    "performance",
    "static roofline: per-category step-time bound and MFU ceiling",
    "",
    needs={"fn"},
)
def atx601_roofline(ctx: LintContext) -> Iterator[Finding]:
    result = _roofline(ctx)
    if result is None or (result.mxu_flops == 0 and result.hbm_bytes == 0):
        return
    chip = result.chip
    exposed = _exposed(ctx)
    bound_ms = result.step_time_lower_bound_s * 1e3
    cats = {row["category"]: row for row in result.category_table()}
    top_k = int(ctx.opt("roofline_top_k"))
    yield Finding(
        "ATX601",
        Severity.INFO,
        chip.name,
        f"static roofline ({chip.name}): step >= {bound_ms:.3f} ms, "
        f"{result.bound_category}-bound, MFU <= {result.static_mfu_bound:.3f} "
        f"— mxu {cats['mxu']['time_ms']:.3f} ms "
        f"({result.mxu_flops / 1e9:.2f} GFLOP), "
        f"hbm {cats['hbm']['time_ms']:.3f} ms "
        f"({human_bytes(int(result.hbm_bytes))}), "
        f"vector {cats['vector']['time_ms']:.3f} ms, "
        f"collective {cats['collective']['time_ms']:.3f} ms "
        f"({human_bytes(int(result.ici_bytes))})",
        "",
        data={
            "chip": chip.name,
            "step_time_lower_bound_ms": bound_ms,
            "static_mfu_bound": result.static_mfu_bound,
            "bound_category": result.bound_category,
            "categories": result.category_table(),
            "mxu_flops": result.mxu_flops,
            "hbm_bytes": int(result.hbm_bytes),
            "ici_bytes": int(result.ici_bytes),
            "padding_waste_fraction": result.padding_waste_fraction,
            "exposed_comms_bytes": int(sum(e.bytes for e in exposed)),
            "top_ops": [
                {
                    "name": d.name,
                    "op_name": d.op_name,
                    "dtype": d.dtype,
                    "flops": d.flops,
                    "bytes": d.bytes,
                    "intensity_flops_per_byte": d.intensity,
                    "dims": {"batch": d.batch, "m": d.m, "n": d.n, "k": d.k},
                    "trip_multiplier": d.mult,
                }
                for d in result.top_dots(top_k)
            ],
        },
    )


@rule(
    "ATX602",
    Severity.WARNING,
    "performance",
    "exposed collective: async start/done pair with no compute between",
    "overlap the collective with independent compute (reorder so the "
    "consumer comes later, or enable the latency-hiding scheduler); until "
    "then the wire time lands on the critical path",
    needs={"fn"},
)
def atx602_exposed_collective(ctx: LintContext) -> Iterator[Finding]:
    exposed = _exposed(ctx)
    for e in sorted(exposed, key=lambda e: -e.exposed_s)[:_MAX_FINDINGS]:
        yield Finding(
            "ATX602",
            Severity.WARNING,
            e.start_name,
            f"{e.op} moves {human_bytes(e.bytes)} "
            f"(~{e.collective_time_s * 1e3:.3f} ms on the wire) but only "
            f"~{e.overlap_compute_s * 1e3:.3f} ms of compute is scheduled "
            f"between its -start and -done — "
            f"~{e.exposed_s * 1e3:.3f} ms of comms sits on the critical "
            f"path every step",
            "",
            data={
                "op": e.op,
                "bytes": e.bytes,
                "collective_ms": e.collective_time_s * 1e3,
                "overlap_compute_ms": e.overlap_compute_s * 1e3,
                "exposed_ms": e.exposed_s * 1e3,
                "computation": e.comp,
            },
        )


@rule(
    "ATX603",
    Severity.WARNING,
    "performance",
    "tiling waste: hot dot dims overrun the native tile by a non-multiple",
    "pad or pick the dim to a multiple of the native tile (lane 128; "
    "sublane 8/16/32 for f32/bf16/int8) — e.g. round d_ff or head_dim up; "
    "the MXU pads silently and burns the difference",
    needs={"fn"},
)
def atx603_tiling_waste(ctx: LintContext) -> Iterator[Finding]:
    result = _roofline(ctx)
    if result is None:
        return
    chip = result.chip
    min_frac = ctx.opt("tiling_waste_fraction")
    min_flops = ctx.opt("tiling_min_waste_flops")
    hits = []
    for d in result.dots:
        padded = padded_dot_flops(d, chip)
        wasted = padded - d.flops
        if padded <= 0 or wasted < min_flops:
            continue
        frac = wasted / padded
        if frac < min_frac:
            continue
        hits.append((wasted, frac, padded, d))
    for wasted, frac, padded, d in sorted(hits, key=lambda t: -t[0])[:_MAX_FINDINGS]:
        sub = chip.native_sublane(d.dtype)
        offending = [
            f"{label}={dim} (tile {tile})"
            for label, dim, tile in (("m", d.m, sub), ("n", d.n, chip.lane),
                                     ("k", d.k, chip.lane))
            if dim > tile and dim % tile
        ]
        yield Finding(
            "ATX603",
            Severity.WARNING,
            d.op_name or d.name,
            f"dot [{d.m}x{d.k}]·[{d.k}x{d.n}] ({d.dtype}"
            f"{', x' + str(d.mult) if d.mult > 1 else ''}) pads "
            f"{', '.join(offending)} — {100 * frac:.1f}% of its MXU FLOPs "
            f"({wasted / 1e9:.2f} GFLOP/step) are tile padding",
            "",
            data={
                "name": d.name,
                "op_name": d.op_name,
                "dtype": d.dtype,
                "dims": {"batch": d.batch, "m": d.m, "n": d.n, "k": d.k},
                "tiles": {"sublane": sub, "lane": chip.lane},
                "flops": d.flops,
                "padded_flops": padded,
                "waste_fraction": frac,
                "wasted_flops": wasted,
            },
        )


@rule(
    "ATX604",
    Severity.WARNING,
    "performance",
    "precision fallback: hot dot upcast to a wider dtype before the MXU",
    "keep the contraction in the narrow dtype (preferred_element_type for "
    "the accumulator instead of converting inputs; for int8/fp8, check "
    "the quantized kernel actually dispatched) — the upcast runs the dot "
    "at a fraction of the narrow peak and doubles its HBM traffic",
    needs={"fn"},
)
def atx604_precision_fallback(ctx: LintContext) -> Iterator[Finding]:
    result = _roofline(ctx)
    if result is None or result.mxu_flops <= 0:
        return
    hot = ctx.opt("precision_hot_fraction") * result.mxu_flops
    hits = [
        d for d in result.dots if d.upcast_from and d.flops >= max(hot, 1.0)
    ]
    for d in sorted(hits, key=lambda d: -d.flops)[:_MAX_FINDINGS]:
        quantized = d.upcast_from in ("s8", "u8", "s4", "u4") or d.upcast_from.startswith("f8")
        kind = (
            "a quantized contraction lowered to a high-precision dot"
            if quantized
            else f"an {d.upcast_from}->{d.result_dtype} upcast before the dot"
        )
        yield Finding(
            "ATX604",
            Severity.WARNING,
            d.op_name or d.name,
            f"hot dot ({d.flops / 1e9:.2f} GFLOP/step, "
            f"{100 * d.flops / result.mxu_flops:.0f}% of MXU work) shows "
            f"{kind} — it runs at the {d.result_dtype} rate instead of "
            f"the {d.upcast_from} peak",
            "",
            data={
                "name": d.name,
                "op_name": d.op_name,
                "upcast_from": d.upcast_from,
                "result_dtype": d.result_dtype,
                "flops": d.flops,
                "share_of_mxu_flops": d.flops / result.mxu_flops,
                "quantized_fallback": quantized,
            },
        )


@rule(
    "ATX605",
    Severity.WARNING,
    "performance",
    "fusion break: elementwise chain materialized to HBM between fusions",
    "a single-consumer kLoop->kLoop handoff this size usually means an "
    "op in the middle blocked fusion (a reshape/transpose, a custom call, "
    "or an xla_fusion size limit) — restructure so the chain fuses, or "
    "checkpoint/remat past the barrier",
    needs={"fn"},
)
def atx605_fusion_break(ctx: LintContext) -> Iterator[Finding]:
    hlo = ctx.compiled_text()
    if hlo is None:
        return
    breaks = find_fusion_breaks(hlo, min_bytes=ctx.opt("fusion_break_bytes"))
    for b in sorted(breaks, key=lambda b: -b.buffer_bytes)[:_MAX_FINDINGS]:
        yield Finding(
            "ATX605",
            Severity.WARNING,
            b.producer,
            f"kLoop fusion {b.producer} materializes "
            f"{human_bytes(b.buffer_bytes)} to HBM whose only consumer is "
            f"kLoop fusion {b.consumer} — "
            f"{human_bytes(b.extra_hbm_bytes)} of avoidable HBM round-trip "
            f"per step",
            "",
            data={
                "producer": b.producer,
                "consumer": b.consumer,
                "buffer_bytes": b.buffer_bytes,
                "extra_hbm_bytes": b.extra_hbm_bytes,
                "computation": b.comp,
            },
        )

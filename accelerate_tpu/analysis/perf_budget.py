"""Ratcheting perf budgets over the ATX601/ATX701/ATX706 static series.

`perf/budgets.json` commits statically-derived numbers per lint scenario —
the MFU ceiling, the exposed-collective bytes, and the tile-padding waste
fraction from the ATX601 roofline, the peak-HBM figure from the ATX701
memory timeline, and the serving planner's static max-slots from ATX706 —
and `atx lint perf|memory --budgets perf/budgets.json` (the `make
lint-perf` / `make lint-memory` lanes) fails when any of them regresses
past tolerance: the static twin of `bench.py --compare`. A PR that
improves a series re-baselines it with `--write-budgets`, so the budget
only moves in the good direction deliberately — a ratchet.

Tolerances are small-but-nonzero because the series, while deterministic
for a given jax/XLA version, shift when the compiler changes fusion or
partitioning decisions; the ratchet should catch model/config mistakes,
not XLA point releases.
"""

from __future__ import annotations

import json
import os
from typing import Any

#: The budgeted series: the first three from every ATX601 `Finding.data`,
#: `peak_hbm_mib` from ATX701, `serve_static_max_slots` from ATX706.
SERIES = (
    "static_mfu_bound",
    "exposed_comms_bytes",
    "padding_waste_fraction",
    "peak_hbm_mib",
    "serve_static_max_slots",
)

# static_mfu_bound may drop (worsen) by at most this relative fraction.
MFU_REL_TOL = 0.02
# exposed_comms_bytes may grow by at most this relative fraction + floor
# (the floor keeps a 0 -> 4-byte wobble from failing the lane).
BYTES_REL_TOL = 0.02
BYTES_ABS_TOL = 1024
# padding_waste_fraction may grow by at most this absolute amount.
FRAC_ABS_TOL = 0.01
# peak_hbm_mib may grow by at most this relative fraction + 1 MiB.
HBM_REL_TOL = 0.02
HBM_ABS_TOL_MIB = 1.0
# serve_static_max_slots may shrink by at most max(1, 2% of the budget).
SLOTS_REL_TOL = 0.02

#: Which rule's Finding.data carries each series.
_SERIES_RULES = {
    "static_mfu_bound": "ATX601",
    "exposed_comms_bytes": "ATX601",
    "padding_waste_fraction": "ATX601",
    "peak_hbm_mib": "ATX701",
    "serve_static_max_slots": "ATX706",
}


def extract_series(report: Any) -> dict[str, float] | None:
    """The budget series from a Report's ATX601/ATX701/ATX706 findings, or
    None when the scenario produced no roofline AND no memory timeline
    (build failed, or no compiled step)."""
    out: dict[str, float] = {}
    for f in getattr(report, "findings", []):
        if f.rule_id not in ("ATX601", "ATX701", "ATX706") or not f.data:
            continue
        for key, rule_id in _SERIES_RULES.items():
            if f.rule_id == rule_id and key in f.data and key not in out:
                out[key] = float(f.data[key])
    return out or None


def load_budgets(path: str) -> dict[str, dict[str, float]]:
    with open(path) as fh:
        doc = json.load(fh)
    return doc.get("scenarios", doc)


def write_budgets(path: str, scenarios: dict[str, dict[str, float]]) -> None:
    doc = {
        "_comment": (
            "Static perf/memory budgets ratcheted by `make lint-perf` and "
            "`make lint-memory` (atx lint perf|memory --budgets "
            "perf/budgets.json). Regenerate with --write-budgets only when "
            "a regression is understood and accepted, or to bank an "
            "improvement. docs/performance.md, docs/static_analysis.md."
        ),
        "scenarios": {
            name: {k: scenarios[name][k] for k in SERIES if k in scenarios[name]}
            for name in sorted(scenarios)
        },
    }
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(doc, fh, indent=2, sort_keys=True)
        fh.write("\n")
    os.replace(tmp, path)


def check_budgets(
    budgets: dict[str, dict[str, float]],
    measured: dict[str, dict[str, float] | None],
) -> list[str]:
    """Violation messages (empty = ratchet holds). A budgeted scenario
    that RAN but produced no series is a violation (its step stopped
    compiling); one that wasn't part of this run is skipped, and
    unbudgeted scenarios/series pass (they get banked by the next
    --write-budgets)."""
    problems: list[str] = []
    for name, budget in sorted(budgets.items()):
        if name not in measured:
            continue
        series = measured[name]
        if series is None:
            problems.append(
                f"{name}: budgeted scenario produced no ATX601/ATX701 "
                "series (step failed to compile, or the rules were "
                "filtered)"
            )
            continue
        old = budget.get("static_mfu_bound")
        new = series.get("static_mfu_bound")
        if old is not None and new is not None and new < old * (1 - MFU_REL_TOL):
            problems.append(
                f"{name}: static_mfu_bound regressed {old:.4f} -> {new:.4f} "
                f"(tolerance -{100 * MFU_REL_TOL:.0f}%)"
            )
        old = budget.get("exposed_comms_bytes")
        new = series.get("exposed_comms_bytes")
        if old is not None and new is not None and new > old * (1 + BYTES_REL_TOL) + BYTES_ABS_TOL:
            problems.append(
                f"{name}: exposed_comms_bytes regressed {int(old)} -> "
                f"{int(new)} (tolerance +{100 * BYTES_REL_TOL:.0f}% + "
                f"{BYTES_ABS_TOL} B)"
            )
        old = budget.get("padding_waste_fraction")
        new = series.get("padding_waste_fraction")
        if old is not None and new is not None and new > old + FRAC_ABS_TOL:
            problems.append(
                f"{name}: padding_waste_fraction regressed {old:.4f} -> "
                f"{new:.4f} (tolerance +{FRAC_ABS_TOL})"
            )
        old = budget.get("peak_hbm_mib")
        new = series.get("peak_hbm_mib")
        if (
            old is not None and new is not None
            and new > old * (1 + HBM_REL_TOL) + HBM_ABS_TOL_MIB
        ):
            problems.append(
                f"{name}: peak_hbm_mib regressed {old:.1f} -> {new:.1f} "
                f"(tolerance +{100 * HBM_REL_TOL:.0f}% + "
                f"{HBM_ABS_TOL_MIB:.0f} MiB)"
            )
        old = budget.get("serve_static_max_slots")
        new = series.get("serve_static_max_slots")
        if old is not None and new is not None:
            floor = old - max(1.0, old * SLOTS_REL_TOL)
            if new < floor:
                problems.append(
                    f"{name}: serve_static_max_slots regressed {int(old)} "
                    f"-> {int(new)} (tolerance -max(1, "
                    f"{100 * SLOTS_REL_TOL:.0f}%))"
                )
    return problems

"""Ratcheting perf budgets over the ATX601 static-roofline series.

`perf/budgets.json` commits three statically-derived numbers per lint
scenario — the MFU ceiling, the exposed-collective bytes, and the
tile-padding waste fraction — and `atx lint perf --budgets perf/budgets.json`
(the `make lint-perf` lane) fails when any of them regresses past
tolerance: the static twin of `bench.py --compare`. A PR that improves a
series re-baselines it with `--write-budgets`, so the budget only moves in
the good direction deliberately — a ratchet.

Tolerances are small-but-nonzero because the series, while deterministic
for a given jax/XLA version, shift when the compiler changes fusion or
partitioning decisions; the ratchet should catch model/config mistakes,
not XLA point releases.
"""

from __future__ import annotations

import json
import os
from typing import Any

#: The budgeted series, as emitted in every ATX601 `Finding.data`.
SERIES = ("static_mfu_bound", "exposed_comms_bytes", "padding_waste_fraction")

# static_mfu_bound may drop (worsen) by at most this relative fraction.
MFU_REL_TOL = 0.02
# exposed_comms_bytes may grow by at most this relative fraction + floor
# (the floor keeps a 0 -> 4-byte wobble from failing the lane).
BYTES_REL_TOL = 0.02
BYTES_ABS_TOL = 1024
# padding_waste_fraction may grow by at most this absolute amount.
FRAC_ABS_TOL = 0.01


def extract_series(report: Any) -> dict[str, float] | None:
    """The budget series from a Report's ATX601 finding, or None when the
    scenario produced no roofline (build failed, or no compiled step)."""
    for f in getattr(report, "findings", []):
        if f.rule_id == "ATX601" and f.data:
            return {k: float(f.data[k]) for k in SERIES if k in f.data}
    return None


def load_budgets(path: str) -> dict[str, dict[str, float]]:
    with open(path) as fh:
        doc = json.load(fh)
    return doc.get("scenarios", doc)


def write_budgets(path: str, scenarios: dict[str, dict[str, float]]) -> None:
    doc = {
        "_comment": (
            "Static perf budgets ratcheted by `make lint-perf` "
            "(atx lint perf --budgets perf/budgets.json). Regenerate with "
            "--write-budgets only when a regression is understood and "
            "accepted, or to bank an improvement. docs/performance.md."
        ),
        "scenarios": {
            name: {k: scenarios[name][k] for k in SERIES if k in scenarios[name]}
            for name in sorted(scenarios)
        },
    }
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(doc, fh, indent=2, sort_keys=True)
        fh.write("\n")
    os.replace(tmp, path)


def check_budgets(
    budgets: dict[str, dict[str, float]],
    measured: dict[str, dict[str, float] | None],
) -> list[str]:
    """Violation messages (empty = ratchet holds). A budgeted scenario
    that RAN but produced no roofline is a violation (its step stopped
    compiling); one that wasn't part of this run is skipped, and
    unbudgeted scenarios pass (they get banked by the next
    --write-budgets)."""
    problems: list[str] = []
    for name, budget in sorted(budgets.items()):
        if name not in measured:
            continue
        series = measured[name]
        if series is None:
            problems.append(
                f"{name}: budgeted scenario produced no ATX601 roofline "
                "(step failed to compile, or the perf rules were filtered)"
            )
            continue
        old = budget.get("static_mfu_bound")
        new = series.get("static_mfu_bound")
        if old is not None and new is not None and new < old * (1 - MFU_REL_TOL):
            problems.append(
                f"{name}: static_mfu_bound regressed {old:.4f} -> {new:.4f} "
                f"(tolerance -{100 * MFU_REL_TOL:.0f}%)"
            )
        old = budget.get("exposed_comms_bytes")
        new = series.get("exposed_comms_bytes")
        if old is not None and new is not None and new > old * (1 + BYTES_REL_TOL) + BYTES_ABS_TOL:
            problems.append(
                f"{name}: exposed_comms_bytes regressed {int(old)} -> "
                f"{int(new)} (tolerance +{100 * BYTES_REL_TOL:.0f}% + "
                f"{BYTES_ABS_TOL} B)"
            )
        old = budget.get("padding_waste_fraction")
        new = series.get("padding_waste_fraction")
        if old is not None and new is not None and new > old + FRAC_ABS_TOL:
            problems.append(
                f"{name}: padding_waste_fraction regressed {old:.4f} -> "
                f"{new:.4f} (tolerance +{FRAC_ABS_TOL})"
            )
    return problems

"""Structured lint results: `Finding` records, severity levels, `Report`.

The analyzer never prints — every rule emits `Finding(rule_id, severity,
path, message, fix_hint)` records and the three surfaces (library API,
`Accelerator.prepare(lint=...)`, the `atx lint` CLI) decide how to render
and when to fail. Severities are an IntEnum so thresholds compare directly
(`f.severity >= Severity.WARNING`).
"""

from __future__ import annotations

import enum
import json
from dataclasses import asdict, dataclass, field
from typing import Iterable, Sequence


class Severity(enum.IntEnum):
    """Finding severity. ERROR findings gate CI (`atx lint` exits non-zero;
    `prepare(lint="error")` raises); WARNING is a probable perf/memory bug;
    INFO is accounting the reader may want (e.g. collective traffic)."""

    INFO = 10
    WARNING = 20
    ERROR = 30

    @classmethod
    def parse(cls, value: "Severity | str") -> "Severity":
        if isinstance(value, Severity):
            return value
        try:
            return cls[str(value).upper()]
        except KeyError:
            raise ValueError(
                f"Unknown severity {value!r}; expected one of "
                f"{[s.name.lower() for s in cls]}"
            ) from None

    def __str__(self) -> str:  # "error", not "Severity.ERROR", in messages
        return self.name.lower()


class AnalysisWarning(UserWarning):
    """Category for lint findings surfaced through `warnings.warn` (the
    `prepare(lint="warn")` path) so callers can filter/promote them."""


@dataclass(frozen=True)
class Finding:
    """One lint hit, anchored to a pytree path (or arg index) in the step.

    ``data`` carries optional machine-readable detail (e.g. ATX404's
    per-collective byte table) for the JSON surfaces; it never renders in
    `format()` and is excluded from equality/hashing so findings stay
    comparable by their human-facing identity."""

    rule_id: str
    severity: Severity
    path: str
    message: str
    fix_hint: str = ""
    data: dict | None = field(default=None, compare=False)

    def format(self) -> str:
        where = f" {self.path}" if self.path else ""
        text = f"{self.rule_id} [{self.severity}]{where}: {self.message}"
        if self.fix_hint:
            text += f"\n    fix: {self.fix_hint}"
        return text

    def to_dict(self) -> dict:
        d = asdict(self)
        d["severity"] = str(self.severity)
        if d.get("data") is None:
            d.pop("data", None)
        return d


class LintError(RuntimeError):
    """Raised by `prepare(lint="error")` / `Report.raise_on_errors` when
    error-severity findings exist. Carries the findings for programmatic
    inspection (`err.findings`)."""

    def __init__(self, findings: Sequence[Finding]):
        self.findings = tuple(findings)
        errors = [f for f in self.findings if f.severity >= Severity.ERROR]
        summary = "\n".join(f.format() for f in (errors or self.findings))
        super().__init__(
            f"step lint found {len(errors)} error-severity finding(s):\n{summary}"
        )


@dataclass
class Report:
    """All findings for one lint target, sorted most-severe first."""

    findings: list[Finding] = field(default_factory=list)
    target: str = ""

    def __post_init__(self) -> None:
        self.findings = sorted(
            self.findings, key=lambda f: (-int(f.severity), f.rule_id, f.path)
        )

    @property
    def has_errors(self) -> bool:
        return any(f.severity >= Severity.ERROR for f in self.findings)

    def filter(
        self,
        min_severity: Severity | str = Severity.INFO,
        family: str | None = None,
    ) -> list[Finding]:
        """Findings at/above a severity; ``family`` is a rule-id prefix
        ("ATX1" selects the sharding family)."""
        min_severity = Severity.parse(min_severity)
        return [
            f
            for f in self.findings
            if f.severity >= min_severity
            and (family is None or f.rule_id.startswith(family))
        ]

    def max_severity(self) -> Severity | None:
        return max((f.severity for f in self.findings), default=None)

    def format(self, min_severity: Severity | str = Severity.INFO) -> str:
        shown = self.filter(min_severity)
        if not shown:
            return "OK — no findings"
        return "\n".join(f.format() for f in shown)

    def to_dict(self) -> dict:
        return {
            "target": self.target,
            "findings": [f.to_dict() for f in self.findings],
        }

    def to_json(self, **kwargs) -> str:
        return json.dumps(self.to_dict(), **kwargs)

    def raise_on_errors(self) -> "Report":
        if self.has_errors:
            raise LintError(self.findings)
        return self

    def extend(self, findings: Iterable[Finding]) -> "Report":
        self.findings = sorted(
            [*self.findings, *findings],
            key=lambda f: (-int(f.severity), f.rule_id, f.path),
        )
        return self

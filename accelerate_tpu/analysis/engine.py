"""Rule engine: trace/lower/compile a step once, run every registered rule.

The analyzer works entirely ahead of time — nothing executes on device:

- `jax.eval_shape` gives output shapes (donation recycling analysis);
- `jax.make_jaxpr` gives the traced program (callback / host-sync rules);
- `jit(...).lower(...)` gives the StableHLO module (donation aliasing — the
  `tf.aliasing_output` markers — plus jax's own "donated buffers were not
  usable" warning, captured here);
- `.compile().as_text()` gives the optimized HLO with the concrete
  collectives GSPMD inserted (byte accounting for accidental gathers) —
  the same machinery `tests/test_sharding_hlo.py` asserts against.

Every artifact is lazy and cached on the `LintContext`; a rule that needs an
artifact the build failed to produce simply skips (the failure itself is
reported once, as ATX002).
"""

from __future__ import annotations

import dataclasses
import warnings
from contextlib import nullcontext
from typing import Any, Callable, Iterable, Iterator, Sequence

import jax
import numpy as np

from ..parallel.mesh import use_mesh
from ..parallel.sharding import (
    ShardingSpecWarning,
    _path_str,
    infer_opt_specs,
    infer_param_specs,
)
from .findings import Finding, Report, Severity

_UNSET = object()

# Tunable thresholds; every lint entry point accepts them as keyword
# overrides (`lint_step(..., gather_bytes_threshold=1 << 20)`).
DEFAULT_OPTIONS: dict[str, Any] = {
    # ATX103: replicated params smaller than this never flag (biases,
    # layernorm scales — replication is the right call for them).
    "replicated_bytes_threshold": 1 << 20,
    # ATX201: an undonated arg flags only when outputs could recycle at
    # least this many of its bytes.
    "donation_bytes_threshold": 1 << 20,
    # ATX403: absolute floor — any single all-gather output this large
    # flags regardless of model size.
    "gather_bytes_threshold": 256 << 20,
    # ATX403: relative trigger — a single all-gather moving this fraction
    # of the TOTAL param bytes (and at least gather_min_bytes) is the
    # "accidental full-param gather" signature.
    "gather_param_fraction": 0.5,
    "gather_min_bytes": 8 << 20,
    # ATX6xx performance family (analysis/roofline.py). `roofline_chip`
    # picks the ChipSpec to rate against: a name from
    # `roofline.CHIP_SPECS` ("v5p"), or None to auto-detect the local
    # device (`cpu` on the container).
    "roofline_chip": None,
    # ATX601: how many highest-FLOP ops get an arithmetic-intensity row.
    "roofline_top_k": 8,
    # ATX602: an async collective is exposed when the compute scheduled
    # between its -start and -done covers less than this fraction of the
    # wire time; pairs below the byte floor never flag.
    "exposed_overlap_fraction": 0.5,
    "exposed_min_bytes": 1 << 20,
    # ATX603: a dot flags when tile padding wastes at least this fraction
    # of its MXU FLOPs AND at least this many absolute FLOPs (keeps tiny
    # CPU-scale models quiet).
    "tiling_waste_fraction": 0.1,
    "tiling_min_waste_flops": 1e9,
    # ATX604: only dots carrying at least this fraction of the step's
    # total dot FLOPs are "hot" enough to flag a precision fallback.
    "precision_hot_fraction": 0.05,
    # ATX605: a fusion break flags when the materialized intermediate is
    # at least this large (one extra HBM write + read per step).
    "fusion_break_bytes": 32 << 20,
    # ATX7xx memory family (analysis/memory.py). `hbm_capacity_bytes`
    # overrides the chip's HBM capacity for the ATX702 OOM gate (None:
    # use `roofline_chip`'s spec) — the seeded-defect tests use it to
    # model a small chip without allocating anything.
    "hbm_capacity_bytes": None,
    # ATX703: a buffer flags when it sits unused for at least this many
    # scheduled instructions between definition and first use AND holds at
    # least this many bytes; top_k bounds the report.
    "liverange_gap_instrs": 100,
    "liverange_min_bytes": 16 << 20,
    "liverange_top_k": 4,
    # ATX704: undonated state live at the peak flags only above this size.
    "donation_peak_min_bytes": 1 << 20,
    # ATX705: XLA temp bytes at the peak flag when they exceed this
    # multiple of the largest single-instruction working set (and the
    # absolute floor keeps CPU-scale toys quiet).
    "temp_blowup_factor": 4.0,
    "temp_blowup_min_bytes": 16 << 20,
}


@dataclasses.dataclass(frozen=True)
class RuleSpec:
    """Registry entry: identity + docs for one rule. ``severity`` is the
    rule's typical/maximum severity (individual findings may be lower,
    e.g. ATX301 downgrades hashable-but-drifting statics to INFO)."""

    rule_id: str
    severity: Severity
    family: str
    summary: str
    fix_hint: str = ""
    needs: frozenset = frozenset()
    fn: Callable[["LintContext"], Iterator[Finding]] | None = None


_RULES: dict[str, RuleSpec] = {}


def rule(
    rule_id: str,
    severity: Severity,
    family: str,
    summary: str,
    fix_hint: str = "",
    needs: Iterable[str] = (),
):
    """Register a rule: a generator ``fn(ctx) -> Iterator[Finding]``.
    ``needs={"fn"}`` marks rules that require a step function (skipped by
    `lint_specs`, which has only shapes and specs)."""

    def deco(fn: Callable) -> Callable:
        _RULES[rule_id] = RuleSpec(
            rule_id, severity, family, summary, fix_hint, frozenset(needs), fn
        )
        return fn

    return deco


def registered_rules() -> list[RuleSpec]:
    return sorted(_RULES.values(), key=lambda r: r.rule_id)


def _leaf_bytes(leaf: Any) -> int:
    return int(np.prod(getattr(leaf, "shape", ()), dtype=np.int64)) * np.dtype(
        leaf.dtype
    ).itemsize


def _flat_with_paths(tree: Any, is_leaf: Callable | None = None) -> list[tuple[str, Any]]:
    flat, _ = jax.tree_util.tree_flatten_with_path(tree, is_leaf=is_leaf)
    return [(_path_str(p), v) for p, v in flat]


def _is_spec(x: Any) -> bool:
    from jax.sharding import PartitionSpec

    return isinstance(x, PartitionSpec)


class LintContext:
    """Everything the rules may inspect, built lazily and cached."""

    def __init__(
        self,
        *,
        fn: Callable | None = None,
        args: Sequence[Any] = (),
        mesh: Any = None,
        donate_argnums: Sequence[int] = (),
        static_argnums: Sequence[int] = (),
        params_shapes: Any = None,
        opt_shapes: Any = None,
        param_specs: Any = None,
        opt_specs: Any = None,
        strategy: Any = None,
        alternates: Sequence[Sequence[Any]] = (),
        host_trace: Any = None,
        processes: int = 1,
        options: dict[str, Any] | None = None,
    ) -> None:
        unknown = set(options or ()) - set(DEFAULT_OPTIONS)
        if unknown:
            raise TypeError(f"Unknown lint option(s): {sorted(unknown)}")
        self.fn = fn
        self.args = tuple(args)
        self.mesh = mesh
        self.donate_argnums = tuple(donate_argnums)
        self.static_argnums = tuple(static_argnums)
        self.params_shapes = params_shapes
        self.opt_shapes = opt_shapes
        self.param_specs = param_specs
        self.opt_specs = opt_specs
        self.strategy = strategy
        self.alternates = tuple(tuple(a) for a in alternates)
        self.host_trace = host_trace
        self.processes = int(processes)
        self.options = {**DEFAULT_OPTIONS, **(options or {})}
        self.spec_warnings: list[ShardingSpecWarning] = []
        self.lowering_warnings: list[warnings.WarningMessage] = []
        self._notes: list[Finding] = []
        self._jitted = _UNSET
        self._jaxpr = _UNSET
        self._lowered = _UNSET
        self._compiled = _UNSET
        self._compiled_text = _UNSET
        self._out_shapes = _UNSET
        self._resolved_param_specs = _UNSET
        self._inference_ran = False

    def opt(self, key: str) -> Any:
        return self.options[key]

    # ------------------------------------------------------------ artifacts
    def _mesh_ctx(self):
        return use_mesh(self.mesh) if self.mesh is not None else nullcontext()

    def _note(self, stage: str, err: Exception) -> None:
        self._notes.append(
            Finding(
                "ATX002",
                Severity.ERROR,
                stage,
                f"step failed to {stage} ahead of time: {type(err).__name__}: {err}",
                "a step that cannot trace/lower/compile abstractly will fail "
                "the same way on the pod; fix this before launching",
            )
        )

    @property
    def jitted(self) -> Callable | None:
        """The step as a jit-wrapped callable. A function that already has a
        ``.lower`` surface (``jax.jit`` product, or the Accelerator's train
        step) is used as-is — its donation/static config is already baked."""
        if self._jitted is _UNSET:
            if self.fn is None:
                self._jitted = None
            elif hasattr(self.fn, "lower"):
                self._jitted = self.fn
            else:
                self._jitted = jax.jit(
                    self.fn,
                    donate_argnums=self.donate_argnums,
                    static_argnums=self.static_argnums,
                )
        return self._jitted

    def jaxpr(self) -> Any:
        """ClosedJaxpr of the step traced on the abstract args, or None."""
        if self._jaxpr is _UNSET:
            self._jaxpr = None
            if self.jitted is not None:
                try:
                    with self._mesh_ctx():
                        self._jaxpr = jax.make_jaxpr(
                            self.jitted, static_argnums=self.static_argnums
                        )(*self.args)
                except Exception as e:
                    self._note("trace", e)
        return self._jaxpr

    def lowered(self) -> Any:
        """`Lowered` for the step, with lowering-time warnings captured
        (jax reports dropped donations as a UserWarning here)."""
        if self._lowered is _UNSET:
            self._lowered = None
            if self.jitted is not None:
                try:
                    with warnings.catch_warnings(record=True) as rec:
                        warnings.simplefilter("always")
                        with self._mesh_ctx():
                            self._lowered = self.jitted.lower(*self.args)
                    self.lowering_warnings = list(rec)
                except Exception as e:
                    self._note("lower", e)
        return self._lowered

    def lowered_text(self) -> str | None:
        low = self.lowered()
        if low is None:
            return None
        try:
            return low.as_text()
        except Exception:
            return None

    def compiled_executable(self) -> Any:
        """The compiled executable (`jax.stages.Compiled`), or None when
        compilation isn't possible here (e.g. the mesh spans more devices
        than this host has). Shared by `compiled_text()` and
        `memory_stats()` so the step compiles exactly once."""
        if self._compiled is _UNSET:
            self._compiled = None
            low = self.lowered()
            if low is not None:
                try:
                    # Donation of sharded args is resolved here, not at
                    # lowering — capture the dropped-donation warnings from
                    # this stage too (rules_donation consumes them).
                    with warnings.catch_warnings(record=True) as rec:
                        warnings.simplefilter("always")
                        with self._mesh_ctx():
                            self._compiled = low.compile()
                    self.lowering_warnings.extend(rec)
                except Exception as e:
                    self._note("compile", e)
        return self._compiled

    def compiled_text(self) -> str | None:
        """Optimized HLO text (post-GSPMD: real collectives), or None when
        compilation isn't possible here."""
        if self._compiled_text is _UNSET:
            self._compiled_text = None
            exe = self.compiled_executable()
            if exe is not None:
                try:
                    self._compiled_text = exe.as_text()
                except Exception as e:
                    self._note("compile", e)
        return self._compiled_text

    def memory_stats(self) -> Any:
        """`compiled.memory_analysis()` (CompiledMemoryStats: argument /
        output / temp / alias bytes), or None when unavailable — the
        ATX7xx cross-check anchor."""
        exe = self.compiled_executable()
        if exe is None:
            return None
        try:
            return exe.memory_analysis()
        except Exception:
            return None

    def flat_arg_paths(self) -> dict[int, str]:
        """Flattened-argument index -> pytree path for the non-static args
        — the entry-parameter order jax compiles, used as the category
        fallback when the HLO's ``op_name`` metadata is stripped."""
        out: dict[int, str] = {}
        i = 0
        for argnum, arg in enumerate(self.args):
            if argnum in self.static_argnums:
                continue
            for path, _ in _flat_with_paths(arg):
                out[i] = path
                i += 1
        return out

    def out_shapes(self) -> Any:
        if self._out_shapes is _UNSET:
            self._out_shapes = None
            if self.jitted is not None:
                static = dict(zip(self.static_argnums,
                                  (self.args[i] for i in self.static_argnums)))
                traced = [a for i, a in enumerate(self.args) if i not in static]

                def closed(*targs):
                    full, it = [], iter(targs)
                    for i in range(len(self.args)):
                        full.append(static[i] if i in static else next(it))
                    return self.fn(*full)

                try:
                    with self._mesh_ctx():
                        self._out_shapes = jax.eval_shape(closed, *traced)
                except Exception as e:
                    self._note("trace", e)
        return self._out_shapes

    # ----------------------------------------------------------- spec logic
    def resolved_param_specs(self) -> Any:
        """Explicit param specs, or specs inferred from (strategy, shapes)
        with `ShardingSpecWarning`s captured for ATX101. None when neither
        is derivable (or inference raised — ATX102 reports why)."""
        if self._resolved_param_specs is _UNSET:
            self._resolved_param_specs = self.param_specs
            if (
                self.param_specs is None
                and self.strategy is not None
                and self.params_shapes is not None
                and self.mesh is not None
            ):
                self._inference_ran = True
                try:
                    with warnings.catch_warnings(record=True) as rec:
                        warnings.simplefilter("always", ShardingSpecWarning)
                        self._resolved_param_specs = infer_param_specs(
                            self.params_shapes, self.mesh, self.strategy
                        )
                    self.spec_warnings = [
                        w.message
                        for w in rec
                        if isinstance(w.message, ShardingSpecWarning)
                    ]
                except ValueError:
                    # Unknown-axis specs; ATX102 reports them from the rule
                    # source, with paths.
                    self._resolved_param_specs = None
        return self._resolved_param_specs

    def iter_spec_leaves(self, which: str = "params") -> Iterator[tuple[str, Any, Any]]:
        """Yield ``(path, shape_leaf, spec)`` joined over the shapes and
        specs trees; empty when either side is missing or they disagree."""
        if which == "params":
            shapes, specs = self.params_shapes, self.resolved_param_specs()
        else:
            shapes, specs = self.opt_shapes, self.opt_specs
        if shapes is None or specs is None:
            return
        shape_flat = _flat_with_paths(shapes)
        spec_flat = _flat_with_paths(specs, is_leaf=_is_spec)
        if len(shape_flat) != len(spec_flat):
            return
        for (path, leaf), (_, spec) in zip(shape_flat, spec_flat):
            yield path, leaf, spec

    def drain_notes(self) -> list[Finding]:
        notes, self._notes = self._notes, []
        # One ATX002 per failed stage is enough.
        seen: set[str] = set()
        return [n for n in notes if not (n.path in seen or seen.add(n.path))]


def _run(ctx: LintContext, only: Sequence[str] | None, strict: bool, target: str) -> Report:
    # Rule modules self-register on import; the package __init__ imports
    # them, but guard against direct-engine use.
    from . import rules_collectives  # noqa: F401
    from . import rules_donation  # noqa: F401
    from . import rules_memory  # noqa: F401
    from . import rules_multihost  # noqa: F401
    from . import rules_perf  # noqa: F401
    from . import rules_recompile  # noqa: F401
    from . import rules_sharding  # noqa: F401

    findings: list[Finding] = []
    for spec in registered_rules():
        if only is not None and spec.rule_id not in only:
            continue
        if "fn" in spec.needs and ctx.fn is None:
            continue
        if "host_trace" in spec.needs and ctx.host_trace is None:
            continue
        try:
            findings.extend(spec.fn(ctx))
        except Exception as e:
            if strict:
                raise
            findings.append(
                Finding(
                    "ATX000",
                    Severity.WARNING,
                    spec.rule_id,
                    f"rule {spec.rule_id} crashed: {type(e).__name__}: {e}",
                    "this is an analyzer bug, not a model bug — report it",
                )
            )
    # Build-stage failures (trace/lower/compile) are findings too, but an
    # existing ERROR (e.g. ATX301's unhashable static) already explains a
    # failed build — don't double-report.
    notes = ctx.drain_notes()
    if notes and not any(f.severity >= Severity.ERROR for f in findings):
        findings.extend(notes)
    return Report(findings=findings, target=target)


def lint_step(
    fn: Callable,
    *abstract_args: Any,
    mesh: Any = None,
    donate_argnums: Sequence[int] = (),
    static_argnums: Sequence[int] = (),
    param_specs: Any = None,
    opt_specs: Any = None,
    params_shapes: Any = None,
    opt_shapes: Any = None,
    strategy: Any = None,
    alternates: Sequence[Sequence[Any]] = (),
    processes: int = 1,
    rules: Sequence[str] | None = None,
    strict: bool = False,
    target: str = "",
    **options: Any,
) -> Report:
    """Lint a jitted (or jittable) step function ahead of time.

    ``abstract_args`` are pytrees of `jax.ShapeDtypeStruct` (attach
    ``sharding=`` so GSPMD sees the real input layout) or concrete arrays —
    nothing is executed either way. ``alternates`` is a list of additional
    call signatures the step will see at runtime (e.g. the ragged last
    batch); the recompilation rules diff them against the primary one.
    ``param_specs``/``opt_specs``/``strategy``/``params_shapes`` feed the
    sharding rules when linting a training step; omit them for a plain
    function and only the fn-shaped rules run. ``processes=N`` additionally
    traces the step once per simulated process (patched
    ``jax.process_index``) and flags process-dependent programs (ATX501).
    Threshold keyword overrides: see `DEFAULT_OPTIONS`.
    """
    ctx = LintContext(
        fn=fn,
        args=abstract_args,
        mesh=mesh,
        donate_argnums=donate_argnums,
        static_argnums=static_argnums,
        params_shapes=params_shapes,
        opt_shapes=opt_shapes,
        param_specs=param_specs,
        opt_specs=opt_specs,
        strategy=strategy,
        alternates=alternates,
        processes=processes,
        options=options or None,
    )
    return _run(ctx, rules, strict, target)


def lint_host_loop(
    loop_fn: Callable[[], Any],
    *,
    processes: int = 2,
    env: Any = None,
    preempted: Sequence[int] = (),
    max_rounds: int = 3,
    rules: Sequence[str] | None = None,
    strict: bool = False,
    target: str = "",
    **options: Any,
) -> Report:
    """Replay a host-side step/save/serve loop once per simulated process
    and lint the recorded collective schedules (the ATX5xx family).

    ``loop_fn`` is a zero-arg callable — it may freely construct
    Accelerators, call `ops` collectives, save checkpoints, read the
    preemption flag, and branch on `jax.process_index()`; every owned
    collective entry point is intercepted (`host_trace.replay_host_loop`).
    ``preempted`` marks simulated processes whose preemption flag starts
    set — the SIGTERM-skew scenario. ``env`` is a common env-delta dict or
    ``{process: {...}}`` per-process deltas.
    """
    from .host_trace import replay_host_loop

    result = replay_host_loop(
        loop_fn,
        processes=processes,
        env=env,
        preempted=preempted,
        max_rounds=max_rounds,
    )
    ctx = LintContext(
        host_trace=result, processes=processes, options=options or None
    )
    report = _run(ctx, rules, strict, target)
    if result.errors:
        report.extend(
            Finding(
                "ATX000",
                Severity.WARNING,
                f"process{p}",
                f"simulated process {p} raised during replay: {msg} — the "
                "collective log for this process may be truncated",
                "if the loop needs real multi-process results to run, gate "
                "the failing section on the replay's patched collectives",
            )
            for p, msg in sorted(result.errors.items())
        )
    return report


def lint_specs(
    params_shapes: Any,
    mesh: Any,
    *,
    strategy: Any = None,
    param_specs: Any = None,
    opt_specs: Any = None,
    opt_shapes: Any = None,
    rules: Sequence[str] | None = None,
    strict: bool = False,
    target: str = "",
    **options: Any,
) -> Report:
    """Sharding-family lint only (no step function): validates the
    strategy's rule table and the inferred/explicit PartitionSpecs against
    the mesh. This is what `Accelerator.prepare(lint=...)` runs before any
    buffer moves."""
    ctx = LintContext(
        params_shapes=params_shapes,
        mesh=mesh,
        strategy=strategy,
        param_specs=param_specs,
        opt_specs=opt_specs,
        opt_shapes=opt_shapes,
        options=options or None,
    )
    return _run(ctx, rules, strict, target)


def lint_training(
    accelerator: Any,
    init_fn: Any,
    tx: Any,
    loss_fn: Callable,
    batch: Any,
    *,
    has_aux: bool = False,
    donate: bool = True,
    batch_alternates: Sequence[Any] = (),
    rng: Any = None,
    processes: int = 1,
    rules: Sequence[str] | None = None,
    strict: bool = False,
    target: str = "",
    **options: Any,
) -> Report:
    """Lint the REAL compiled train step an Accelerator would run — without
    materializing a single parameter.

    ``init_fn`` is the usual `(rng) -> params` initializer (or a concrete /
    abstract params pytree), ``batch`` a pytree of arrays or shape structs.
    Builds the abstract TrainState with the Accelerator's own planned
    shardings attached, compiles `make_train_step`'s product, and runs every
    rule family over it.
    """
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec

    from ..accelerator import DynamicLossScale, TrainState
    from ..parallel.mesh import batch_sharding
    from ..parallel.sharding import to_named_shardings

    mesh = accelerator.mesh
    rng = rng if rng is not None else accelerator.rng
    if callable(init_fn):
        params_shapes = jax.eval_shape(init_fn, rng)
    else:
        params_shapes = jax.eval_shape(lambda: init_fn)
    param_specs, opt_specs = accelerator._resolve_specs(params_shapes, tx)
    opt_shapes = jax.eval_shape(tx.init, params_shapes)

    def sds(leaf: Any, sharding: Any) -> jax.ShapeDtypeStruct:
        return jax.ShapeDtypeStruct(tuple(leaf.shape), leaf.dtype, sharding=sharding)

    replicated = NamedSharding(mesh, PartitionSpec())
    params_sds = jax.tree.map(sds, params_shapes, to_named_shardings(param_specs, mesh))
    opt_sds = jax.tree.map(sds, opt_shapes, to_named_shardings(opt_specs, mesh))
    loss_scale = None
    if accelerator.policy.compute_dtype == jnp.float16:
        loss_scale = jax.tree.map(
            lambda l: sds(l, replicated), jax.eval_shape(DynamicLossScale.create)
        )
    state_sds = TrainState(
        step=jax.ShapeDtypeStruct((), jnp.int32, sharding=replicated),
        params=params_sds,
        opt_state=opt_sds,
        tx=tx,
        loss_scale=loss_scale,
    )
    bsh = batch_sharding(mesh)
    to_batch_sds = lambda b: jax.tree.map(lambda x: sds(x, bsh), b)

    step = accelerator.make_train_step(loss_fn, has_aux=has_aux, donate=donate)
    jitted = accelerator._train_steps[id(step)]
    return lint_step(
        jitted,
        state_sds,
        to_batch_sds(batch),
        mesh=mesh,
        donate_argnums=(0,) if donate else (),
        param_specs=param_specs,
        opt_specs=opt_specs,
        params_shapes=params_shapes,
        opt_shapes=opt_shapes,
        strategy=accelerator.strategy,
        alternates=[(state_sds, to_batch_sds(b)) for b in batch_alternates],
        processes=processes,
        rules=rules,
        strict=strict,
        target=target,
        **options,
    )

"""Opt-in runtime mirror of the simulated collective log (``ATX_COLLECTIVE_LOG=1``).

The simulated-process harness (`analysis/host_trace.py`) predicts the
collective schedule ahead of time; this module records the REAL one. When
``ATX_COLLECTIVE_LOG=1`` every owned collective entry point — the `ops/`
host collectives, `ProcessState.wait_for_everyone`, and the checkpoint
commit barrier in `resilience/commit.py` — appends one JSON line per call
to ``collective_log_<proc>.jsonl`` under ``ATX_COLLECTIVE_LOG_DIR``
(default: CWD). Multi-process fault-injection tests then call
`verify_agreement` on the directory to assert every process issued the
same ordered schedule — the runtime ground truth the ATX5xx rules
approximate statically.

Call sites import lazily (`_maybe_collective_log` helpers at each site do
the env check before importing this module), so the analysis package stays
off the hot path unless the flag is set.

Process-index resolution order: ``ATX_COLLECTIVE_LOG_PROC`` (explicit test
override) → `jax.process_index()` if jax is already imported →
``ATX_PROCESS_ID`` (launcher contract) → 0.
"""

from __future__ import annotations

import json
import os
import sys
import time
import traceback
from typing import Any

ENV_FLAG = "ATX_COLLECTIVE_LOG"
ENV_DIR = "ATX_COLLECTIVE_LOG_DIR"
ENV_PROC = "ATX_COLLECTIVE_LOG_PROC"

LOG_FILE = "collective_log_{proc}.jsonl"


def enabled() -> bool:
    return os.environ.get(ENV_FLAG, "").strip().lower() in ("1", "true", "yes", "on")


def _process_index() -> int:
    explicit = os.environ.get(ENV_PROC)
    if explicit is not None:
        try:
            return int(explicit)
        except ValueError:
            pass
    if "jax" in sys.modules:
        try:
            import jax

            return int(jax.process_index())
        except Exception:  # pragma: no cover - jax mid-init
            pass
    try:
        return int(os.environ.get("ATX_PROCESS_ID", "0"))
    except ValueError:
        return 0


def log_path(proc: int | None = None) -> str:
    proc = _process_index() if proc is None else proc
    root = os.environ.get(ENV_DIR) or os.getcwd()
    return os.path.join(root, LOG_FILE.format(proc=proc))


def runtime_record(kind: str, name: str, signature: str = "") -> None:
    """Append one collective event to this process's JSONL log. Never raises
    (a logging failure must not take down a training step)."""
    if not enabled():
        return
    try:
        proc = _process_index()
        entry = {
            "kind": kind,
            "name": name,
            "signature": signature,
            "process": proc,
            "time": time.time(),
            "stack": traceback.format_stack(limit=8)[:-1],
        }
        path = log_path(proc)
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "a") as f:
            f.write(json.dumps(entry) + "\n")
    except Exception:  # pragma: no cover - best-effort by contract
        pass


def read_logs(directory: str) -> dict[int, list[dict[str, Any]]]:
    """Load every ``collective_log_<proc>.jsonl`` under ``directory`` into
    ``{proc: [event, ...]}`` (events in issue order)."""
    logs: dict[int, list[dict[str, Any]]] = {}
    if not os.path.isdir(directory):
        return logs
    for fname in sorted(os.listdir(directory)):
        if not (fname.startswith("collective_log_") and fname.endswith(".jsonl")):
            continue
        try:
            proc = int(fname[len("collective_log_") : -len(".jsonl")])
        except ValueError:
            continue
        events = []
        with open(os.path.join(directory, fname)) as f:
            for line in f:
                line = line.strip()
                if line:
                    try:
                        events.append(json.loads(line))
                    except ValueError:
                        continue
        logs[proc] = events
    return logs


STORE_PREFIX = "collective_logs/"


def ship_log(
    store, *, process_index: int | None = None, prefix: str = STORE_PREFIX
) -> str | None:
    """Upload this process's collective log to a replicate `ObjectStore`
    (key ``collective_logs/collective_log_<proc>.jsonl``), so the runtime
    schedule survives the VM on exit/preemption. Returns the key, or None
    when there is no log to ship. Raises on store errors — the caller
    (`Accelerator._ship_collective_log`) owns the best-effort swallow."""
    proc = _process_index() if process_index is None else process_index
    path = log_path(proc)
    if not os.path.exists(path):
        return None
    key = prefix + LOG_FILE.format(proc=proc)
    store.put_file(path, key)
    return key


def fetch_logs(store, directory: str, *, prefix: str = STORE_PREFIX) -> list[str]:
    """Download every shipped collective log under ``prefix`` into
    ``directory`` (named so `read_logs`/`verify_agreement` work on it
    directly). Returns the local paths fetched."""
    os.makedirs(directory, exist_ok=True)
    fetched: list[str] = []
    for key in store.list(prefix):
        fname = os.path.basename(key)
        if not (fname.startswith("collective_log_") and fname.endswith(".jsonl")):
            continue
        local = os.path.join(directory, fname)
        store.get_file(key, local)
        fetched.append(local)
    return fetched


def verify_agreement(directory: str) -> list[str]:
    """Align the recorded per-process logs; return human-readable mismatch
    descriptions (empty = every process issued the same collective schedule).

    This is the runtime analog of the ATX5xx alignment: same event count,
    and at each position the same (kind, name, signature) triple.
    """
    logs = read_logs(directory)
    if len(logs) < 2:
        return []
    procs = sorted(logs)
    base_proc = procs[0]
    base = logs[base_proc]
    errors: list[str] = []
    for proc in procs[1:]:
        other = logs[proc]
        for i, (a, b) in enumerate(zip(base, other)):
            ka = (a["kind"], a["name"], a.get("signature", ""))
            kb = (b["kind"], b["name"], b.get("signature", ""))
            if ka != kb:
                errors.append(
                    f"event {i}: process {base_proc} issued {ka} but "
                    f"process {proc} issued {kb}"
                )
                break
        else:
            if len(base) != len(other):
                errors.append(
                    f"event count mismatch: process {base_proc} issued "
                    f"{len(base)} collective(s), process {proc} issued "
                    f"{len(other)}"
                )
    return errors

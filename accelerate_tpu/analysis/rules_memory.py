"""ATX7xx — static memory rules over the compiled-HLO HBM timeline.

ATX6xx bounds *speed* ahead of time; this family bounds *memory*.
Everything derives from one `analysis/memory.py` liveness sweep over
`LintContext.compiled_text()` (the scheduled, post-GSPMD module), anchored
against the executable's own `compiled.memory_analysis()` totals:

- **ATX701** (info, always) — the peak-HBM report: static peak live
  bytes, the instruction at the peak, per-category attribution (params /
  opt state / KV / inputs / activations / collective scratch / XLA
  temps), and headroom vs the `--chip` ChipSpec's HBM. The full timeline
  series plus the two budget series `perf/budgets.json` ratchets
  (`peak_hbm_mib`, and `serve_static_max_slots` from the capacity
  planner) ride in `Finding.data` for `--json` consumers.
- **ATX702** (error) — OOM ahead of time: the static peak exceeds the
  chip's HBM. Fails `lint="error"` before any buffer moves.
- **ATX703** (warning) — live-range waste: a top-K buffer sits unused for
  ≥N scheduled instructions between definition and first use (remat or
  reorder it closer to its consumer).
- **ATX704** (warning) — at-peak donation miss: refines ATX201 by
  reporting only undonated state actually live *at the peak*, with the
  bytes donating it would cut from the peak.
- **ATX705** (warning) — temp blowup: XLA temp buffers (layout/precision
  copies) at the peak exceeding a multiple of the largest single
  instruction's working set — the materialized-upcast signature ATX604
  sees only as compute.

Thresholds: the `hbm_capacity_bytes` / `liverange_*` /
`donation_peak_min_bytes` / `temp_blowup_*` entries in
`engine.DEFAULT_OPTIONS`.
"""

from __future__ import annotations

from collections import Counter
from typing import Iterator

from .engine import LintContext, rule
from .findings import Finding, Severity
from .hbm import human_bytes
from .memory import MemoryTimeline, build_timeline
from .roofline import chip_spec_for

_MAX_FINDINGS = 8
_UNSET = object()


def timeline_for(ctx: LintContext) -> MemoryTimeline | None:
    """One shared HBM-timeline sweep per LintContext (cached on the ctx).
    ATX105 (analysis/rules_sharding.py) also reads this to cite the
    compiled figure next to its first-order arithmetic."""
    cached = getattr(ctx, "_memory_timeline", _UNSET)
    if cached is not _UNSET:
        return cached
    hlo = ctx.compiled_text()
    timeline = None
    if hlo is not None:
        timeline = build_timeline(hlo, param_paths=ctx.flat_arg_paths())
    ctx._memory_timeline = timeline
    return timeline


def _capacity_bytes(ctx: LintContext) -> int:
    override = ctx.opt("hbm_capacity_bytes")
    if override:
        return int(override)
    return chip_spec_for(ctx.opt("roofline_chip")).hbm_bytes


@rule(
    "ATX701",
    Severity.INFO,
    "memory",
    "static HBM timeline: peak live bytes, attribution, chip headroom",
    "",
    needs={"fn"},
)
def atx701_peak_hbm(ctx: LintContext) -> Iterator[Finding]:
    t = timeline_for(ctx)
    if t is None or t.peak_bytes <= 0:
        return
    chip = chip_spec_for(ctx.opt("roofline_chip"))
    capacity = _capacity_bytes(ctx)
    headroom = 1.0 - t.peak_bytes / capacity
    cats = ", ".join(
        f"{k} {human_bytes(v)}"
        for k, v in sorted(t.categories_at_peak.items(), key=lambda kv: -kv[1])
        if v
    )
    stats = ctx.memory_stats()
    stats_dict = None
    cross = {}
    if stats is not None:
        stats_dict = {
            attr: int(getattr(stats, f"{attr}_size_in_bytes", 0) or 0)
            for attr in ("argument", "output", "temp", "alias")
        }
        cross = t.cross_check(stats)
    yield Finding(
        "ATX701",
        Severity.INFO,
        chip.name,
        f"static peak HBM {human_bytes(t.peak_bytes)} at "
        f"{t.peak_instr} [{t.peak_index}/{t.n_instructions}] — {cats} — "
        f"{100 * headroom:.1f}% headroom vs {chip.name} "
        f"({human_bytes(capacity)})",
        "",
        data={
            "chip": chip.name,
            "peak_hbm_bytes": t.peak_bytes,
            "peak_hbm_mib": t.peak_bytes / 2**20,
            "hbm_capacity_bytes": capacity,
            "headroom_fraction": headroom,
            "peak_index": t.peak_index,
            "peak_instr": t.peak_instr,
            "categories_at_peak": dict(t.categories_at_peak),
            "argument_bytes": t.argument_bytes,
            "output_bytes": t.output_bytes,
            "alias_bytes": t.alias_bytes,
            "n_buffers": len(t.buffers),
            "n_instructions": t.n_instructions,
            "memory_analysis": stats_dict,
            "cross_check": cross,
            "timeline": t.downsampled_series(),
        },
    )


@rule(
    "ATX702",
    Severity.ERROR,
    "memory",
    "OOM ahead of time: static peak HBM exceeds the chip's capacity",
    "this program cannot fit: shrink the per-device footprint (more model "
    "parallelism, smaller batch, remat/offload activations, narrower "
    "optimizer state) before launching — the pod would OOM at this exact "
    "instruction",
    needs={"fn"},
)
def atx702_oom_ahead_of_time(ctx: LintContext) -> Iterator[Finding]:
    t = timeline_for(ctx)
    if t is None:
        return
    capacity = _capacity_bytes(ctx)
    if t.peak_bytes <= capacity:
        return
    chip = chip_spec_for(ctx.opt("roofline_chip"))
    over = t.peak_bytes - capacity
    cats = ", ".join(
        f"{k} {human_bytes(v)}"
        for k, v in sorted(t.categories_at_peak.items(), key=lambda kv: -kv[1])
        if v
    )
    yield Finding(
        "ATX702",
        Severity.ERROR,
        chip.name,
        f"static peak HBM {human_bytes(t.peak_bytes)} exceeds {chip.name} "
        f"capacity {human_bytes(capacity)} by {human_bytes(over)} "
        f"(at {t.peak_instr}, instruction {t.peak_index} of "
        f"{t.n_instructions}) — {cats}",
        "",
        data={
            "chip": chip.name,
            "peak_hbm_bytes": t.peak_bytes,
            "hbm_capacity_bytes": capacity,
            "over_bytes": over,
            "peak_instr": t.peak_instr,
            "categories_at_peak": dict(t.categories_at_peak),
        },
    )


@rule(
    "ATX703",
    Severity.WARNING,
    "memory",
    "live-range waste: large buffer idle between definition and first use",
    "the buffer holds HBM across a region that never reads it — define it "
    "closer to its consumer, or remat it there (jax.checkpoint / "
    "jax.remat) so the bytes are free in between",
    needs={"fn"},
)
def atx703_liverange_waste(ctx: LintContext) -> Iterator[Finding]:
    t = timeline_for(ctx)
    if t is None:
        return
    gap_min = int(ctx.opt("liverange_gap_instrs"))
    bytes_min = int(ctx.opt("liverange_min_bytes"))
    top_k = int(ctx.opt("liverange_top_k"))
    hits = []
    for b in t.buffers:
        if b.op == "parameter" or b.bytes < bytes_min or b.first_use < 0:
            continue
        gap = b.first_use - b.def_index
        if gap >= gap_min:
            hits.append((b.bytes * gap, gap, b))
    for _, gap, b in sorted(hits, key=lambda h: -h[0])[:top_k]:
        yield Finding(
            "ATX703",
            Severity.WARNING,
            b.name,
            f"{b.op} buffer {b.name} ({human_bytes(b.bytes)}) is defined at "
            f"instruction {b.def_index} but first read at {b.first_use} — "
            f"idle for {gap} of {t.n_instructions} scheduled instructions "
            f"while holding its HBM",
            "",
            data={
                "name": b.name,
                "op": b.op,
                "bytes": b.bytes,
                "def_index": b.def_index,
                "first_use": b.first_use,
                "last_use": b.last_use,
                "idle_instructions": gap,
                "byte_instructions": b.bytes * gap,
            },
        )


@rule(
    "ATX704",
    Severity.WARNING,
    "memory",
    "at-peak donation miss: undonated state live at the peak instruction",
    "donate the argument (donate_argnums, or Accelerator donate=True) — "
    "the output of matching shape/dtype can recycle its storage, cutting "
    "exactly these bytes from the static peak",
    needs={"fn"},
)
def atx704_donation_miss_at_peak(ctx: LintContext) -> Iterator[Finding]:
    t = timeline_for(ctx)
    if t is None:
        return
    bytes_min = int(ctx.opt("donation_peak_min_bytes"))
    # Count-aware signature match against the output tuple (mirrors
    # ATX201): each output element can recycle at most one argument.
    available = Counter(t.output_signatures)
    peak = t.peak_index
    hits = []
    for b in t.buffers:
        if (
            b.op != "parameter"
            or b.donated
            or b.category not in ("params", "opt_state", "kv")
            or b.bytes < bytes_min
            or not (b.def_index <= peak <= b.last_use)
        ):
            continue
        sig = (b.dtype, tuple(b.shape))
        if available.get(sig, 0) <= 0:
            continue
        available[sig] -= 1
        hits.append(b)
    for b in sorted(hits, key=lambda b: -b.bytes)[:_MAX_FINDINGS]:
        where = b.path or f"arg {b.param_number}"
        yield Finding(
            "ATX704",
            Severity.WARNING,
            where,
            f"{b.category} argument {where} ({human_bytes(b.bytes)}, "
            f"{b.dtype}{list(b.shape)}) is live at the peak instruction "
            f"({t.peak_instr}) without donation while an output of the "
            f"same shape/dtype exists — donating it cuts the static peak "
            f"by {human_bytes(b.bytes)}",
            "",
            data={
                "path": b.path,
                "param_number": b.param_number,
                "category": b.category,
                "bytes": b.bytes,
                "dtype": b.dtype,
                "shape": list(b.shape),
                "peak_index": t.peak_index,
            },
        )


@rule(
    "ATX705",
    Severity.WARNING,
    "memory",
    "temp blowup: XLA temp buffers at the peak dwarf the working set",
    "temps this large are usually materialized layout/precision copies "
    "(bf16->f32 upcasts, transposes feeding an unfused consumer) — keep "
    "the compute dtype narrow end-to-end and check ATX604/ATX605 for the "
    "op that forced the copy",
    needs={"fn"},
)
def atx705_temp_blowup(ctx: LintContext) -> Iterator[Finding]:
    t = timeline_for(ctx)
    if t is None:
        return
    temp_bytes = t.categories_at_peak.get("xla_temp", 0)
    threshold = max(
        ctx.opt("temp_blowup_factor") * t.max_working_set_bytes,
        ctx.opt("temp_blowup_min_bytes"),
    )
    if temp_bytes <= threshold:
        return
    peak = t.peak_index
    temps = sorted(
        (
            b for b in t.buffers
            if b.category == "xla_temp" and b.def_index <= peak <= b.last_use
        ),
        key=lambda b: -b.bytes,
    )
    yield Finding(
        "ATX705",
        Severity.WARNING,
        t.peak_instr,
        f"XLA temp buffers hold {human_bytes(temp_bytes)} at the peak — "
        f"{t.max_working_set_bytes and temp_bytes / t.max_working_set_bytes or 0:.1f}x "
        f"the largest single-instruction working set "
        f"({human_bytes(t.max_working_set_bytes)}); top temps: "
        + ", ".join(
            f"{b.name} ({b.op}, {human_bytes(b.bytes)})" for b in temps[:4]
        ),
        "",
        data={
            "temp_bytes_at_peak": temp_bytes,
            "max_working_set_bytes": t.max_working_set_bytes,
            "threshold_bytes": int(threshold),
            "top_temps": [
                {"name": b.name, "op": b.op, "bytes": b.bytes}
                for b in temps[:_MAX_FINDINGS]
            ],
        },
    )

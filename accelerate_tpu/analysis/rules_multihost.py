"""ATX5xx — multi-host SPMD consistency of the collective schedule.

Input: a `HostTraceResult` from `host_trace.replay_host_loop` (the
`lint_host_loop` surface, `atx lint --multihost N`), or — for ATX501's
function variant — the step function itself traced once per simulated
process. The rules align the N per-process collective logs and report the
FIRST divergence with both processes' call stacks; one divergence yields
exactly one finding, classified by cause:

- **ATX501** divergent jitted/host collective sequence — a branch on
  `process_index` changes what gets compiled or dispatched;
- **ATX502** a process-local host flag guards a collective-bearing path
  without group agreement (the PR-4 preemption bug: a SIGTERM flag
  checked locally instead of or-reduced);
- **ATX503** barrier/commit ordering mismatch in the save path;
- **ATX504** per-process RNG values feeding a collective that expects
  replicated operands (missing — or extra — `fold_in(process_index)`);
- **ATX505** collective issue order derived from unordered dict/set
  iteration (same multiset of collectives, different order).

Classification precedence on the first divergence: ATX502 (the diverging
processes read different flag values just before) → ATX503 (a barrier or
commit-barrier event is on either side of the split) → ATX505 (the
remaining schedules are permutations of each other) → ATX501 (everything
else). ATX504 scans the *aligned* prefix independently — it is a value
property, not a schedule property, and is WARNING severity because
per-process keys are sometimes intended (data-parallel sampling).
"""

from __future__ import annotations

from typing import Any, Iterator

from .engine import LintContext, rule
from .findings import Finding, Severity
from .host_trace import HostEvent, HostTraceResult, sanitize_signature, simulated_process

_FAMILY = "multihost"


# ------------------------------------------------------------------ alignment
def _indent(text: str, prefix: str = "      ") -> str:
    return prefix + text.replace("\n", "\n" + prefix)


def _analysis(ctx: LintContext) -> dict[str, Any]:
    """Align the per-process collective logs once per context; every ATX5xx
    rule reads the cached verdict so one divergence → one finding."""
    cached = getattr(ctx, "_atx5_analysis", None)
    if cached is not None:
        return cached
    result: HostTraceResult = ctx.host_trace
    seqs = {p: result.collectives(p) for p in sorted(result.logs)}
    min_len = min((len(s) for s in seqs.values()), default=0)
    div: int | None = None
    for i in range(min_len):
        if len({seqs[p][i].key for p in seqs}) > 1:
            div = i
            break
    if div is None and len({len(s) for s in seqs.values()}) > 1:
        div = min_len  # one process's schedule simply ends early
    events: dict[int, HostEvent | None] = {}
    if div is not None:
        events = {p: (seqs[p][div] if div < len(seqs[p]) else None) for p in seqs}
    verdict, flags = _classify(result, seqs, div, events)
    info = {
        "seqs": seqs,
        "index": div,
        "events": events,
        "rule": verdict,
        "flags": flags,
    }
    ctx._atx5_analysis = info
    return info


def _classify(
    result: HostTraceResult,
    seqs: dict[int, list[HostEvent]],
    div: int | None,
    events: dict[int, HostEvent | None],
) -> tuple[str | None, dict[int, HostEvent]]:
    if div is None:
        return None, {}
    # ATX502: the diverging processes read DIFFERENT values from a host
    # flag just before splitting — the un-agreed conditional is the cause.
    flags: dict[int, HostEvent] = {}
    for p in seqs:
        limit = (
            events[p].index if events[p] is not None else len(result.logs.get(p, []))
        )
        reads = [
            e
            for e in result.logs.get(p, [])
            if e.kind == "flag_read" and e.index < limit
        ]
        if reads:
            flags[p] = reads[-1]
    if len(flags) >= 2 and len({e.fingerprint for e in flags.values()}) > 1:
        return "ATX502", flags
    # ATX503: a barrier (or the commit file-barrier) sits on either side of
    # the split — save-path ordering bug.
    kinds = {e.kind for e in events.values() if e is not None}
    if kinds & {"barrier", "precommit"}:
        return "ATX503", flags
    # ATX505: every process issues the SAME multiset of collectives from
    # here on, just in different orders — unordered-container iteration.
    suffixes = {
        p: tuple(sorted(repr(e.key) for e in seq[div:])) for p, seq in seqs.items()
    }
    if len(set(suffixes.values())) == 1:
        return "ATX505", flags
    return "ATX501", flags


def _divergence_message(
    seqs: dict[int, list[HostEvent]],
    div: int,
    events: dict[int, HostEvent | None],
) -> str:
    lines = [f"first cross-process divergence at collective #{div}:"]
    for p in sorted(events):
        e = events[p]
        if e is None:
            lines.append(
                f"  process {p}: issues NO further collectives "
                f"({len(seqs[p])} total) — its peers block forever in theirs"
            )
        else:
            lines.append(f"  process {p}: {e.describe()}")
            lines.append(_indent(e.stack, "      "))
    return "\n".join(lines)


def _path_for(div: int | None) -> str:
    return f"collective#{div}" if div is not None else ""


# ---------------------------------------------------------------------- rules
@rule(
    "ATX501",
    Severity.ERROR,
    _FAMILY,
    "collective schedule diverges across processes (process_index branch "
    "changes what gets compiled/dispatched)",
    fix_hint="make every process issue the identical collective sequence: "
    "hoist process_index branches out of collective-bearing paths, or make "
    "the branch outcome a group decision (broadcast/reduce it first)",
)
def _atx501(ctx: LintContext) -> Iterator[Finding]:
    if ctx.host_trace is not None:
        info = _analysis(ctx)
        if info["rule"] != "ATX501":
            return
        yield Finding(
            "ATX501",
            Severity.ERROR,
            _path_for(info["index"]),
            _divergence_message(info["seqs"], info["index"], info["events"]),
            "every process must run the same host collective schedule; on a "
            "real pod the minority rank wedges the whole group",
        )
        return
    # Function variant (`lint_step(fn, ..., processes=N)`): trace the step
    # once per simulated process and require identical jaxprs. jax's trace
    # cache is keyed on the fn+avals, NOT on our patched process_index —
    # clear it so each process really re-traces.
    if ctx.fn is None or ctx.processes < 2:
        return
    import jax

    texts: dict[int, str] = {}
    failures: dict[int, str] = {}
    for p in range(ctx.processes):
        with simulated_process(p, ctx.processes):
            jax.clear_caches()
            try:
                with ctx._mesh_ctx():
                    jaxpr = jax.make_jaxpr(
                        ctx.jitted, static_argnums=ctx.static_argnums
                    )(*ctx.args)
                texts[p] = sanitize_signature(str(jaxpr))
            except Exception as e:
                failures[p] = f"{type(e).__name__}: {e}"
    jax.clear_caches()  # drop traces made under a patched process_index
    if failures and texts:
        yield Finding(
            "ATX501",
            Severity.ERROR,
            "trace",
            "the step traces on some processes but fails on others: "
            + "; ".join(f"process {p}: {msg}" for p, msg in sorted(failures.items())),
            "a step that only traces for certain process indices compiles "
            "different programs per rank — or crashes a subset of the pod",
        )
        return
    if len(set(texts.values())) > 1:
        base_p = min(texts)
        base_lines = texts[base_p].splitlines()
        for p in sorted(texts):
            if texts[p] == texts[base_p]:
                continue
            other_lines = texts[p].splitlines()
            where = next(
                (
                    i
                    for i, (a, b) in enumerate(zip(base_lines, other_lines))
                    if a != b
                ),
                min(len(base_lines), len(other_lines)),
            )

            def _line(lines: list[str], i: int) -> str:
                return lines[i].strip() if i < len(lines) else "<end of program>"

            yield Finding(
                "ATX501",
                Severity.ERROR,
                "trace",
                f"the step traces to DIFFERENT programs on process {base_p} "
                f"vs process {p} (first differing jaxpr line {where}:\n"
                f"  process {base_p}: {_line(base_lines, where)}\n"
                f"  process {p}: {_line(other_lines, where)})"
                " — a branch on process_index changes what gets compiled, so "
                "GSPMD emits mismatched collective programs across the pod",
                "compute rank-dependent values as data (e.g. pass "
                "process_index as an input) instead of branching the trace "
                "on it",
            )
            return


@rule(
    "ATX502",
    Severity.ERROR,
    _FAMILY,
    "host flag guards a collective-bearing path without group agreement",
    fix_hint="or-reduce the flag across processes before acting on it "
    "(the fixed preemption handler reduces the SIGTERM flag with "
    "ops.reduce(..., 'sum') at every step entry)",
    needs=("host_trace",),
)
def _atx502(ctx: LintContext) -> Iterator[Finding]:
    info = _analysis(ctx)
    if info["rule"] != "ATX502":
        return
    flags: dict[int, HostEvent] = info["flags"]
    lines = [
        "a process-local flag sent the processes down different "
        "collective paths (the PR-4 hang class):",
    ]
    for p in sorted(flags):
        e = flags[p]
        lines.append(
            f"  process {p} read {e.name} -> {e.fingerprint or '?'} at"
        )
        lines.append(_indent(e.stack, "      "))
    lines.append(_divergence_message(info["seqs"], info["index"], info["events"]))
    yield Finding(
        "ATX502",
        Severity.ERROR,
        _path_for(info["index"]),
        "\n".join(lines),
        "a SIGTERM/maintenance notice lands on ONE process; every process "
        "must agree (reduce the flag) before any of them changes its "
        "collective schedule",
    )


@rule(
    "ATX503",
    Severity.ERROR,
    _FAMILY,
    "barrier/commit ordering mismatch across processes in the save path",
    fix_hint="issue barriers and commit-barrier halves in the same order on "
    "every process; keep proc-0-only work (commit_dir, rotation) strictly "
    "between the same pair of barriers everywhere",
    needs=("host_trace",),
)
def _atx503(ctx: LintContext) -> Iterator[Finding]:
    info = _analysis(ctx)
    if info["rule"] != "ATX503":
        return
    yield Finding(
        "ATX503",
        Severity.ERROR,
        _path_for(info["index"]),
        _divergence_message(info["seqs"], info["index"], info["events"]),
        "a barrier one process never reaches (or reaches out of order) "
        "deadlocks the checkpoint commit on a real pod",
    )


@rule(
    "ATX504",
    Severity.WARNING,
    _FAMILY,
    "per-process RNG value feeds a collective that expects replicated "
    "operands",
    fix_hint="either all processes pass the SAME key (drop the "
    "fold_in(process_index)) or the collective is data-parallel by design "
    "— then silence this by folding in explicitly at the call site",
    needs=("host_trace",),
)
def _atx504(ctx: LintContext) -> Iterator[Finding]:
    info = _analysis(ctx)
    seqs = info["seqs"]
    if not seqs:
        return
    min_len = min(len(s) for s in seqs.values())
    end = min_len if info["index"] is None else info["index"]
    for i in range(end):
        events = {p: seqs[p][i] for p in seqs}
        fps = {e.fingerprint for e in events.values()}
        if len(fps) <= 1:
            continue
        if not any("(2,):uint32" in e.signature for e in events.values()):
            continue
        procs = sorted(events)
        a, b = events[procs[0]], events[procs[-1]]
        yield Finding(
            "ATX504",
            Severity.WARNING,
            f"collective#{i}",
            f"{a.describe()} receives a DIFFERENT PRNG-key value on each "
            f"process (process {procs[0]} vs process {procs[-1]} "
            "fingerprints differ) — replication-expecting collectives "
            "(broadcast/reduce of sampling state) silently desync when fed "
            "per-process keys:\n"
            f"  process {procs[0]}:\n{_indent(a.stack)}\n"
            f"  process {procs[-1]}:\n{_indent(b.stack)}",
            "a missing or extra jax.random.fold_in(key, process_index) is "
            "the usual cause",
        )


@rule(
    "ATX505",
    Severity.ERROR,
    _FAMILY,
    "collective issue order derived from unordered dict/set iteration",
    fix_hint="iterate collections in a deterministic order (sorted keys / "
    "insertion-ordered dicts shared by construction) before issuing "
    "collectives from them",
    needs=("host_trace",),
)
def _atx505(ctx: LintContext) -> Iterator[Finding]:
    info = _analysis(ctx)
    if info["rule"] != "ATX505":
        return
    yield Finding(
        "ATX505",
        Severity.ERROR,
        _path_for(info["index"]),
        "every process issues the SAME collectives but in DIFFERENT "
        "orders — the signature of iterating an unordered container:\n"
        + _divergence_message(info["seqs"], info["index"], info["events"]),
        "mismatched collective order deadlocks exactly like a missing one: "
        "each rank blocks in a different op",
    )


# ------------------------------------------------------- prepare() spec check
def spec_consistency_findings(build: Any, processes: int) -> list[Finding]:
    """Run a spec-producing callable once per simulated process and flag
    divergent results — `Accelerator.prepare(lint=...)` uses this (under
    ``ATX_LINT_PROCESSES``) to prove the planned parameter shardings don't
    depend on `process_index`."""
    reprs: dict[int, str] = {}
    for p in range(processes):
        with simulated_process(p, processes):
            try:
                reprs[p] = sanitize_signature(repr(build()))
            except Exception as e:
                reprs[p] = f"<failed: {type(e).__name__}: {e}>"
    if len(set(reprs.values())) <= 1:
        return []
    base_p = min(reprs)
    detail = "\n".join(
        f"  process {p}: {'identical' if reprs[p] == reprs[base_p] and p != base_p else reprs[p][:200]}"
        for p in sorted(reprs)
    )
    return [
        Finding(
            "ATX501",
            Severity.ERROR,
            "prepare",
            "the planned parameter shardings differ across processes — "
            "every process must compute identical PartitionSpecs or GSPMD "
            "compiles mismatched programs:\n" + detail,
            "sharding strategy decisions must not read process_index",
        )
    ]

"""Static roofline model over compiled (post-GSPMD) HLO — no steps run.

The optimized HLO `LintContext.compiled_text()` already produces names every
op with its result shape, operand shapes, contracting dims, and loop
structure, and a chip-generation spec table supplies the peaks — so a
classical roofline bound (Williams et al., CACM 2009) is computable ahead
of time, on the CPU container, with zero weights materialized:

- every instruction is parsed (shapes, dtypes, operands, the call graph of
  fusions / while bodies / called computations, with while trip counts
  recovered from the loop-condition `compare(iv, constant)` pattern);
- each op is bucketed **MXU** (dot/convolution FLOPs at the dtype's peak —
  looking *through* upcast converts so a bf16 model compiled by the CPU
  backend still rates at bf16 peak), **vector** (elementwise FLOPs at VPU
  peak), **HBM** (bytes moved at HBM bandwidth — fusions count their
  materialized operands/outputs once, their internal elementwise traffic
  stays on-chip), or **collective** (per-device result bytes at ICI
  bandwidth);
- the static step-time lower bound is the max over per-resource busy times
  (each resource is serial with itself; perfect overlap is assumed across
  resources — hence a true lower bound), and the **static MFU upper
  bound** is MXU busy time over that bound: the utilization ceiling no
  amount of scheduling can beat for this program on this chip.

Also computed here, for the ATX6xx rules that share the parse: per-dot
tile-padding waste against the native (sublane x 128) tile, dots fed by
precision-fallback upcasts, and kLoop-fusion chains materializing large
intermediates to HBM. Chip peaks are approximate public numbers — they set
the *ratios* the bound needs, not benchmarked truth.
"""

from __future__ import annotations

import dataclasses
import math
import re
from collections import defaultdict
from typing import Any, Iterator

# --------------------------------------------------------------- chip specs

#: HLO dtype -> (itemsize, peak-table class). Classes: mxu-rated dtypes map
#: to a peak_flops key; everything else rates at the widest ("f32") peak.
_DTYPE_BYTES = {
    "pred": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16, "f8e4m3fn": 1, "f8e5m2": 1, "f8e4m3": 1, "f8e5m2fnuz": 1,
}

_PEAK_CLASS = {
    "bf16": "bf16", "f16": "bf16",
    "s8": "int8", "u8": "int8", "s4": "int8", "u4": "int8",
    "f8e4m3fn": "f8", "f8e5m2": "f8", "f8e4m3": "f8", "f8e5m2fnuz": "f8",
}


@dataclasses.dataclass(frozen=True)
class ChipSpec:
    """Per-generation peaks the roofline rates against. ``peak_flops`` keys
    are peak classes ("bf16", "f32", "int8", "f8"); ``sublane`` is the f32
    sublane count — narrower dtypes pack ``sublane * (4 // itemsize)``."""

    name: str
    peak_flops: dict[str, float]
    hbm_bytes_per_sec: float
    ici_bytes_per_sec: float
    vmem_bytes: int
    vector_flops_per_sec: float
    hbm_bytes: int = 16 << 30   # per-chip HBM capacity (ATX7xx memory lint)
    sublane: int = 8
    lane: int = 128

    def peak_for(self, dtype: str) -> float:
        cls = _PEAK_CLASS.get(dtype, "f32")
        return self.peak_flops.get(cls) or self.peak_flops["f32"]

    def native_sublane(self, dtype: str) -> int:
        itemsize = _DTYPE_BYTES.get(dtype, 4)
        return self.sublane * max(4 // max(itemsize, 1), 1)


# Approximate public per-chip numbers (dense matmul peaks, HBM/ICI
# bandwidth per chip, VMEM). The `cpu` entry is a stand-in so the analysis
# runs end-to-end on the CPU container — its *ratios* (compute:HBM ~2.5
# FLOP/byte) are chosen TPU-shaped so category attribution stays sane.
CHIP_SPECS: dict[str, ChipSpec] = {
    "v4": ChipSpec(
        "v4",
        {"bf16": 275e12, "f32": 68.75e12, "int8": 275e12, "f8": 275e12},
        1228e9, 300e9, 128 << 20, 4.3e12, hbm_bytes=32 << 30,
    ),
    "v5e": ChipSpec(
        "v5e",
        {"bf16": 197e12, "f32": 49.25e12, "int8": 394e12, "f8": 394e12},
        819e9, 200e9, 128 << 20, 3.1e12, hbm_bytes=16 << 30,
    ),
    "v5p": ChipSpec(
        "v5p",
        {"bf16": 459e12, "f32": 114.75e12, "int8": 918e12, "f8": 918e12},
        2765e9, 600e9, 128 << 20, 7.2e12, hbm_bytes=95 << 30,
    ),
    "v6e": ChipSpec(
        "v6e",
        {"bf16": 918e12, "f32": 229.5e12, "int8": 1836e12, "f8": 1836e12},
        1640e9, 448e9, 128 << 20, 14.3e12, hbm_bytes=32 << 30,
    ),
    "cpu": ChipSpec(
        "cpu",
        {"bf16": 50e9, "f32": 50e9, "int8": 100e9, "f8": 100e9},
        # Host-RAM stand-in sized like a v5e so capacity findings stay
        # TPU-shaped on the CPU container.
        20e9, 10e9, 32 << 20, 5e9, hbm_bytes=16 << 30,
    ),
}

_DEVICE_KIND_PREFIXES = (
    ("TPU v6", "v6e"), ("TPU v5p", "v5p"), ("TPU v5 lite", "v5e"),
    ("TPU v5e", "v5e"), ("TPU v5", "v5p"), ("TPU v4", "v4"),
)


def chip_spec_for(chip: "str | Any | None" = None) -> ChipSpec:
    """Resolve a ChipSpec from a spec-table name, a jax Device (via
    ``device_kind``), or None (auto-detect the local device; `cpu` when no
    TPU is attached)."""
    if isinstance(chip, str):
        if chip in CHIP_SPECS:
            return CHIP_SPECS[chip]
        kind = chip
    elif chip is not None and hasattr(chip, "device_kind"):
        kind = chip.device_kind
    else:
        import jax

        kind = getattr(jax.devices()[0], "device_kind", "cpu")
    for prefix, name in _DEVICE_KIND_PREFIXES:
        if kind.startswith(prefix):
            return CHIP_SPECS[name]
    return CHIP_SPECS["cpu"]


# --------------------------------------------------------------- HLO parse

_SHAPE_RE = re.compile(r"([a-z][a-z0-9]*)\[([0-9,]*)\]")
_COMP_HEAD_RE = re.compile(r"^(?:ENTRY\s+)?%?([\w.\-]+)\s*\(.*\)\s*->")
_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%([\w.\-]+)\s*=\s*"
    r"(?P<type>\([^=]*?\)|[a-z][a-z0-9]*\[[0-9,]*\](?:\{[^}]*\})?)\s*"
    r"(?P<op>[\w\-]+)\("
)
_OPERAND_RE = re.compile(
    r"(?:([a-z][a-z0-9]*)\[([0-9,]*)\](?:\{[^}]*\})?\s+)?%([\w.\-]+)"
)
_CALLED_RE = re.compile(
    r"(?P<kind>calls|to_apply|body|condition|true_computation|"
    r"false_computation|branch_computations)=\{?%?([^,\s){]+)"
)
_DIMS_RE = re.compile(r"lhs_contracting_dims=\{([0-9,]*)\}")
_BATCH_DIMS_RE = re.compile(r"lhs_batch_dims=\{([0-9,]*)\}")
_OP_NAME_RE = re.compile(r'op_name="([^"]*)"')
_CONST_VAL_RE = re.compile(r"constant\((-?[0-9]+)\)")
_TRIP_COUNT_RE = re.compile(r'"known_trip_count":\{"n":"([0-9]+)"\}')

# Zero-cost bookkeeping ops: no bytes move (bitcast is a layout pun; tuples
# and parameters alias existing buffers).
_FREE_OPS = frozenset({
    "parameter", "constant", "tuple", "get-tuple-element", "bitcast",
    "after-all", "partition-id", "replica-id", "iota", "rng-bit-generator",
    "opt-barrier", "add-dependency", "domain",
})
# Control-flow ops whose cost lives in their called computations.
_CONTROL_OPS = frozenset({"while", "conditional", "call", "fusion"})

_COLLECTIVE_BASE = (
    "all-gather", "all-reduce", "reduce-scatter", "all-to-all",
    "collective-permute",
)


def collective_base(op: str) -> str | None:
    """`all-gather-start` / `all-gather` -> `all-gather`; None otherwise."""
    for base in _COLLECTIVE_BASE:
        if op == base or op == base + "-start" or op == base + "-done":
            return base
    return None


@dataclasses.dataclass
class HloInstr:
    """One parsed HLO instruction."""

    name: str
    op: str
    dtype: str          # result dtype ("tuple" for tuple-typed results)
    shape: tuple[int, ...]
    out_bytes: int
    operands: list[tuple[str, tuple[int, ...], str]]  # (dtype, shape, name)
    attrs: str
    comp: str
    index: int          # position within its computation
    op_name: str = ""

    @property
    def operand_bytes(self) -> int:
        return sum(
            _elems(s) * _DTYPE_BYTES.get(d, 4) for d, s, _ in self.operands
        )


@dataclasses.dataclass
class HloComputation:
    name: str
    instrs: list[HloInstr]
    by_name: dict[str, HloInstr]


def _elems(shape: tuple[int, ...]) -> int:
    n = 1
    for d in shape:
        n *= d
    return n


def _parse_type(text: str) -> tuple[str, tuple[int, ...], int]:
    """(dtype, shape, total_bytes) for a result type; tuple types sum their
    element bytes and report dtype "tuple" with the first element's shape."""
    matches = _SHAPE_RE.findall(text)
    if not matches:
        return "tuple", (), 0
    total = sum(
        _elems(tuple(int(d) for d in dims.split(",") if d))
        * _DTYPE_BYTES.get(dt, 4)
        for dt, dims in matches
    )
    first_dt, first_dims = matches[0]
    shape = tuple(int(d) for d in first_dims.split(",") if d)
    dtype = first_dt if len(matches) == 1 else "tuple"
    return dtype, shape, total


def _split_operands(line: str, op: str) -> tuple[str, str]:
    """(operand_text, attrs_text) — balanced-paren split at the opcode."""
    start = line.index(op + "(") + len(op)
    depth, i = 0, start
    while i < len(line):
        if line[i] == "(":
            depth += 1
        elif line[i] == ")":
            depth -= 1
            if depth == 0:
                break
        i += 1
    return line[start + 1 : i], line[i + 1 :]


def parse_hlo_module(text: str) -> dict[str, HloComputation]:
    """Parse optimized HLO text into computations of instructions."""
    comps: dict[str, HloComputation] = {}
    current: HloComputation | None = None
    entry_marker: str | None = None
    for raw in text.splitlines():
        # `/*index=5*/` comments inside wide tuple types would defeat the
        # type regex (they contain `=` and `/`); they carry no information.
        if "/*" in raw:
            raw = re.sub(r"/\*.*?\*/", "", raw)
        line = raw.rstrip()
        if not line:
            continue
        if not line.startswith(" ") and ("{" in line) and "->" in line:
            m = _COMP_HEAD_RE.match(line.strip())
            if m:
                current = HloComputation(m.group(1), [], {})
                comps[current.name] = current
                if line.lstrip().startswith("ENTRY"):
                    entry_marker = current.name
                continue
        if current is None:
            continue
        if line.strip() == "}":
            current = None
            continue
        m = _INSTR_RE.match(line)
        if not m:
            continue
        op = m.group("op")
        dtype, shape, out_bytes = _parse_type(m.group("type"))
        try:
            operand_text, attrs = _split_operands(line, op)
        except ValueError:
            operand_text, attrs = "", ""
        if op == "constant" and operand_text:
            # The literal lives in the operand slot; keep scalar values
            # reachable (while_trip_count reads them through attrs).
            attrs = f"constant({operand_text})" + attrs
        operands = [
            (
                od if od else "",
                tuple(int(d) for d in dims.split(",") if d) if od else (),
                name,
            )
            for od, dims, name in _OPERAND_RE.findall(operand_text)
        ]
        op_name_m = _OP_NAME_RE.search(attrs)
        instr = HloInstr(
            name=m.group(1),
            op=op,
            dtype=dtype,
            shape=shape,
            out_bytes=out_bytes,
            operands=operands,
            attrs=attrs,
            comp=current.name,
            index=len(current.instrs),
            op_name=op_name_m.group(1) if op_name_m else "",
        )
        current.instrs.append(instr)
        current.by_name[instr.name] = instr
    if entry_marker is not None:
        for comp in comps.values():
            comp.entry = comp.name == entry_marker  # type: ignore[attr-defined]
    return comps


def entry_computation(comps: dict[str, HloComputation]) -> HloComputation | None:
    for comp in comps.values():
        if getattr(comp, "entry", False):
            return comp
    return None


def _resolve_operand(
    instr: HloInstr, i: int, comp: HloComputation
) -> tuple[str, tuple[int, ...], str]:
    """Operand i with dtype/shape filled from the defining instruction when
    the text carried only a bare %name."""
    dtype, shape, name = instr.operands[i]
    if not dtype:
        definition = comp.by_name.get(name)
        if definition is not None:
            return definition.dtype, definition.shape, name
    return dtype, shape, name


def while_trip_count(
    comps: dict[str, HloComputation], cond_name: str
) -> int:
    """Trip count recovered from the `compare(iv, constant), direction=LT`
    pattern lax.scan/fori lower to; 1 when the pattern is absent (a bound
    the analysis can still work with — it only *under*counts loop work)."""
    comp = comps.get(cond_name)
    if comp is None:
        return 1
    for instr in comp.instrs:
        if instr.op != "compare" or "direction=LT" not in instr.attrs:
            continue
        for _, _, opname in instr.operands:
            definition = comp.by_name.get(opname)
            if definition is not None and definition.op == "constant":
                m = _CONST_VAL_RE.search(
                    definition.attrs
                ) or _CONST_VAL_RE.search(opname)
                if m:
                    return max(int(m.group(1)), 1)
        # constant folded inline into the compare line
        m = _CONST_VAL_RE.search(instr.attrs)
        if m:
            return max(int(m.group(1)), 1)
    return 1


def iter_costed_instrs(
    comps: dict[str, HloComputation],
) -> Iterator[tuple[HloInstr, int, str]]:
    """Yield (instr, multiplier, mode) over every instruction reachable from
    the entry computation. ``multiplier`` is the product of enclosing while
    trip counts; ``mode`` is "full" (count FLOPs and bytes) or "flops"
    (fusion bodies: internal traffic stays on-chip, only MXU work counts).
    Scalar reduction regions and loop conditions are skipped."""
    entry = entry_computation(comps)
    if entry is None:
        return
    # (comp name, multiplier, mode); visited keyed the same way so shared
    # computations called from two sites are costed once per site.
    stack: list[tuple[str, int, str]] = [(entry.name, 1, "full")]
    seen: set[tuple[str, int, str]] = set()
    while stack:
        comp_name, mult, mode = stack.pop()
        key = (comp_name, mult, mode)
        if key in seen:
            continue
        seen.add(key)
        comp = comps.get(comp_name)
        if comp is None:
            continue
        for instr in comp.instrs:
            yield instr, mult, mode
            for m in _CALLED_RE.finditer(instr.attrs):
                kind, target = m.group("kind"), m.group(2).strip("%{} ")
                if kind == "condition":
                    continue
                if kind == "body":
                    # XLA annotates statically-known loops directly; fall
                    # back to the condition's `compare(iv, K), LT` pattern.
                    known = _TRIP_COUNT_RE.search(instr.attrs)
                    if known:
                        trips = max(int(known.group(1)), 1)
                    else:
                        trips = 1
                        for mm in _CALLED_RE.finditer(instr.attrs):
                            if mm.group("kind") == "condition":
                                trips = while_trip_count(
                                    comps, mm.group(2).strip("%{} ")
                                )
                    stack.append((target, mult * trips, mode))
                elif kind == "calls" and instr.op == "fusion":
                    stack.append((target, mult, "flops"))
                elif kind == "to_apply" and instr.op in (
                    "reduce", "reduce-window", "scatter", "all-reduce",
                    "reduce-scatter", "sort", "select-and-scatter",
                ) or collective_base(instr.op):
                    continue  # scalar regions: negligible
                else:
                    stack.append((target, mult, mode))


# --------------------------------------------------------------- cost model

# Elementwise/vector-ish ops: FLOPs ~ output elements (transcendentals
# weighted heavier).
_VECTOR_OPS = {
    "add": 1, "subtract": 1, "multiply": 1, "divide": 4, "maximum": 1,
    "minimum": 1, "compare": 1, "select": 1, "negate": 1, "abs": 1,
    "exponential": 8, "log": 8, "tanh": 10, "logistic": 10, "rsqrt": 4,
    "sqrt": 4, "power": 10, "cosine": 8, "sine": 8, "erf": 10,
    "exponential-minus-one": 8, "log-plus-one": 8, "convert": 1,
    "reduce": 1, "reduce-window": 1, "clamp": 2, "round-nearest-even": 1,
    "floor": 1, "ceil": 1, "sign": 1, "and": 1, "or": 1, "xor": 1, "not": 1,
}


@dataclasses.dataclass
class DotInfo:
    """One dot/convolution with its roofline-relevant numbers."""

    name: str
    op_name: str
    dtype: str               # rated dtype (looked through upcast converts)
    result_dtype: str
    flops: float
    bytes: int
    mult: int
    m: int
    n: int
    k: int
    batch: int
    upcast_from: str = ""    # source dtype when an operand was upcast

    @property
    def intensity(self) -> float:
        return self.flops / max(self.bytes, 1)


def _dot_dims(instr: HloInstr, comp: HloComputation) -> tuple[int, int, int, int]:
    """(batch, M, N, K) for a dot from its operand shapes + contracting and
    batch dims."""
    lhs_d, lhs_shape, _ = _resolve_operand(instr, 0, comp)
    contracting = [
        int(d)
        for d in (_DIMS_RE.search(instr.attrs).group(1).split(",")
                  if _DIMS_RE.search(instr.attrs) else ["-1"])
        if d not in ("", "-1")
    ]
    batch_dims = [
        int(d)
        for d in (_BATCH_DIMS_RE.search(instr.attrs).group(1).split(",")
                  if _BATCH_DIMS_RE.search(instr.attrs) else [])
        if d != ""
    ]
    if not lhs_shape:
        # No shape info: fall back to output-only accounting.
        return 1, _elems(instr.shape), 1, 1
    k = 1
    for d in contracting:
        if 0 <= d < len(lhs_shape):
            k *= lhs_shape[d]
    batch = 1
    for d in batch_dims:
        if 0 <= d < len(lhs_shape):
            batch *= lhs_shape[d]
    m = 1
    for d, size in enumerate(lhs_shape):
        if d not in contracting and d not in batch_dims:
            m *= size
    out = _elems(instr.shape)
    n = max(out // max(batch * m, 1), 1)
    return batch, m, n, k


def _conv_flops(instr: HloInstr, comp: HloComputation) -> float:
    """2 * out_elems * (kernel spatial x in-channels), in-channels inferred
    from the rhs shape and the dim_labels output-feature position."""
    _, rhs_shape, _ = _resolve_operand(instr, 1, comp)
    out = _elems(instr.shape)
    if not rhs_shape:
        return 2.0 * out
    m = re.search(r"dim_labels=\w*_(\w+)->", instr.attrs)
    co = 1
    if m and "o" in m.group(1) and len(m.group(1)) == len(rhs_shape):
        co = rhs_shape[m.group(1).index("o")]
    else:
        co = rhs_shape[-1]
    return 2.0 * out * (_elems(rhs_shape) / max(co, 1))


def _rated_dtype(instr: HloInstr, comp: HloComputation) -> tuple[str, str]:
    """(rated dtype, upcast source) for a dot: when an operand is a convert
    from a narrower float/int (bf16->f32, s8->bf16...), rate the dot at the
    SOURCE dtype — that is what the program meant, and what a TPU MXU would
    run — and report the upcast for ATX604."""
    rated = instr.dtype
    upcast_from = ""
    best_bytes = _DTYPE_BYTES.get(rated, 4)
    for i in range(min(len(instr.operands), 2)):
        od, _, oname = _resolve_operand(instr, i, comp)
        src = od
        definition = comp.by_name.get(oname)
        if definition is not None and definition.op == "convert" and definition.operands:
            src_d, _, _ = _resolve_operand(definition, 0, comp)
            if src_d:
                src = src_d
        nbytes = _DTYPE_BYTES.get(src, 4)
        if src in _PEAK_CLASS and nbytes < best_bytes:
            rated, best_bytes = src, nbytes
            if definition is not None and definition.op == "convert":
                upcast_from = src
    return rated, upcast_from


_CALLS_RE = re.compile(r"calls=%?([\w.\-]+)")


def _fusion_hbm_bytes(instr: HloInstr, comps: dict[str, HloComputation]) -> int:
    """HBM bytes a fusion actually moves. The naive operands+output total
    wildly overcounts fusions that slice into big buffers: a fused
    dynamic-slice reads only the slice, and a fused dynamic-update-slice
    writes only the update into an aliased buffer (the scan-carry pattern —
    charging the full stacked array once per trip would dominate every
    loop)."""
    default = instr.operand_bytes + instr.out_bytes
    m = _CALLS_RE.search(instr.attrs)
    fused = comps.get(m.group(1)) if m else None
    if fused is None:
        return default
    savings = 0
    for fi in fused.instrs:
        if fi.op == "dynamic-slice" and fi.operands:
            od, osh, _ = _resolve_operand(fi, 0, fused)
            savings += max(
                _elems(osh) * _DTYPE_BYTES.get(od, 4) - fi.out_bytes, 0
            )
        elif fi.op == "dynamic-update-slice" and len(fi.operands) >= 2:
            od, osh, _ = _resolve_operand(fi, 0, fused)
            ud, ush, _ = _resolve_operand(fi, 1, fused)
            big = _elems(osh) * _DTYPE_BYTES.get(od, 4)
            upd = _elems(ush) * _DTYPE_BYTES.get(ud, 4)
            savings += 2 * max(big - upd, 0)
    return max(default - savings, 0)


@dataclasses.dataclass
class RooflineResult:
    """Per-category busy times + the derived step-time / MFU bounds."""

    chip: ChipSpec
    mxu_flops: float = 0.0
    mxu_time_s: float = 0.0
    vector_flops: float = 0.0
    vector_time_s: float = 0.0
    hbm_bytes: float = 0.0
    hbm_time_s: float = 0.0
    ici_bytes: float = 0.0
    ici_time_s: float = 0.0
    dots: list[DotInfo] = dataclasses.field(default_factory=list)
    padded_mxu_flops: float = 0.0

    @property
    def step_time_lower_bound_s(self) -> float:
        return max(
            self.mxu_time_s, self.vector_time_s, self.hbm_time_s,
            self.ici_time_s, 1e-12,
        )

    @property
    def static_mfu_bound(self) -> float:
        """Ceiling on achievable MFU: MXU busy time over the bound (1.0
        when the program is purely compute-bound)."""
        if self.mxu_time_s <= 0:
            return 0.0
        return min(self.mxu_time_s / self.step_time_lower_bound_s, 1.0)

    @property
    def bound_category(self) -> str:
        times = {
            "mxu": self.mxu_time_s, "vector": self.vector_time_s,
            "hbm": self.hbm_time_s, "collective": self.ici_time_s,
        }
        return max(times, key=lambda k: times[k])

    @property
    def padding_waste_fraction(self) -> float:
        """Fraction of MXU FLOPs spent on tile padding (dims > one native
        tile that are not tile multiples; sub-tile dims are model-scale
        choices, not tiling bugs, and don't count)."""
        if self.padded_mxu_flops <= 0:
            return 0.0
        return max(1.0 - self.mxu_flops / self.padded_mxu_flops, 0.0)

    def top_dots(self, k: int = 8) -> list[DotInfo]:
        return sorted(self.dots, key=lambda d: -d.flops)[:k]

    def category_table(self) -> list[dict]:
        return [
            {"category": "mxu", "flops": self.mxu_flops,
             "time_ms": self.mxu_time_s * 1e3},
            {"category": "vector", "flops": self.vector_flops,
             "time_ms": self.vector_time_s * 1e3},
            {"category": "hbm", "bytes": int(self.hbm_bytes),
             "time_ms": self.hbm_time_s * 1e3},
            {"category": "collective", "bytes": int(self.ici_bytes),
             "time_ms": self.ici_time_s * 1e3},
        ]


def padded_dot_flops(d: DotInfo, chip: ChipSpec) -> float:
    """FLOPs after rounding each dim up to its native tile — only dims
    LARGER than one tile pad (a 64-wide model on a 128-lane MXU is a model
    choice; a 513-wide dim is a tiling bug)."""
    sub = chip.native_sublane(d.dtype)

    def pad(dim: int, tile: int) -> int:
        if dim <= tile:
            return dim
        return math.ceil(dim / tile) * tile

    return 2.0 * d.batch * pad(d.m, sub) * pad(d.n, chip.lane) * pad(d.k, chip.lane) * d.mult


def analyze_hlo(text: str, chip: ChipSpec) -> RooflineResult:
    """Run the roofline over one optimized-HLO module."""
    comps = parse_hlo_module(text)
    result = RooflineResult(chip=chip)
    for instr, mult, mode in iter_costed_instrs(comps):
        comp = comps[instr.comp]
        if instr.op in ("dot", "convolution"):
            if instr.op == "dot":
                batch, m, n, k = _dot_dims(instr, comp)
                flops = 2.0 * batch * m * n * k
            else:
                flops = _conv_flops(instr, comp)
                batch, m, n, k = 1, _elems(instr.shape), 1, 1
            rated, upcast = _rated_dtype(instr, comp)
            nbytes = (instr.operand_bytes + instr.out_bytes) * mult
            info = DotInfo(
                name=instr.name,
                op_name=instr.op_name,
                dtype=rated,
                result_dtype=instr.dtype,
                flops=flops * mult,
                bytes=nbytes,
                mult=mult,
                m=m, n=n, k=k, batch=batch,
                upcast_from=upcast,
            )
            result.dots.append(info)
            result.mxu_flops += info.flops
            result.mxu_time_s += info.flops / chip.peak_for(rated)
            result.padded_mxu_flops += padded_dot_flops(info, chip)
            if mode == "full":
                result.hbm_bytes += nbytes
                result.hbm_time_s += nbytes / chip.hbm_bytes_per_sec
            continue
        if mode != "full":
            continue  # fusion internals: on-chip traffic
        base = collective_base(instr.op)
        if base is not None:
            if instr.op.endswith("-done"):
                continue  # the matching -start carried the bytes
            nbytes = instr.out_bytes * mult
            result.ici_bytes += nbytes
            result.ici_time_s += nbytes / chip.ici_bytes_per_sec
            continue
        if instr.op in _FREE_OPS or instr.op in ("while", "conditional", "call"):
            continue
        if instr.op in ("dynamic-slice", "slice", "gather"):
            # Reads only the sliced region, not the (possibly huge,
            # loop-stacked) operand: one slice-sized read + one write.
            nbytes = 2 * instr.out_bytes * mult
        elif instr.op in ("dynamic-update-slice", "scatter") and len(instr.operands) >= 2:
            # Reads + writes an update-sized region of an aliased buffer.
            ud, us, _ = _resolve_operand(instr, 1, comps[instr.comp])
            nbytes = 2 * _elems(us) * _DTYPE_BYTES.get(ud, 4) * mult
        elif instr.op == "fusion":
            nbytes = _fusion_hbm_bytes(instr, comps) * mult
        else:
            nbytes = (instr.operand_bytes + instr.out_bytes) * mult
        result.hbm_bytes += nbytes
        result.hbm_time_s += nbytes / chip.hbm_bytes_per_sec
        weight = _VECTOR_OPS.get(instr.op)
        if weight:
            flops = float(weight) * _elems(instr.shape) * mult
            result.vector_flops += flops
            result.vector_time_s += flops / chip.vector_flops_per_sec
    return result


# ------------------------------------------------- exposed-collective scan

@dataclasses.dataclass
class ExposedCollective:
    """An async `-start`/`-done` pair with too little compute between them
    to hide the wire time: the collective sits on the critical path."""

    op: str
    start_name: str
    bytes: int
    collective_time_s: float
    overlap_compute_s: float
    comp: str

    @property
    def exposed_s(self) -> float:
        return max(self.collective_time_s - self.overlap_compute_s, 0.0)


def find_exposed_collectives(
    text: str,
    chip: ChipSpec,
    *,
    min_bytes: int = 1 << 20,
    overlap_fraction: float = 0.5,
) -> list[ExposedCollective]:
    """Scan every computation for async collective start/done pairs and
    rate the compute scheduled between them (dot FLOP time + fusion HBM
    time) against the collective's wire time; pairs covering less than
    ``overlap_fraction`` of it are exposed. Synchronous (non `-start`)
    collectives are not judged — backends without async lowering (the CPU
    container) would flag everything."""
    comps = parse_hlo_module(text)
    out: list[ExposedCollective] = []
    for comp in comps.values():
        starts: dict[str, HloInstr] = {
            i.name: i for i in comp.instrs if i.op.endswith("-start")
            and collective_base(i.op)
        }
        if not starts:
            continue
        for done in comp.instrs:
            if not done.op.endswith("-done") or not collective_base(done.op):
                continue
            start = next(
                (starts[name] for _, _, name in done.operands if name in starts),
                None,
            )
            if start is None:
                continue
            nbytes = start.out_bytes
            if nbytes < min_bytes:
                continue
            wire_s = nbytes / chip.ici_bytes_per_sec
            overlap_s = 0.0
            for between in comp.instrs[start.index + 1 : done.index]:
                if between.op in ("dot", "convolution"):
                    batch, m, n, k = _dot_dims(between, comp)
                    overlap_s += (2.0 * batch * m * n * k) / chip.peak_for(
                        between.dtype
                    )
                elif between.op == "fusion":
                    overlap_s += (
                        between.operand_bytes + between.out_bytes
                    ) / chip.hbm_bytes_per_sec
            if overlap_s < overlap_fraction * wire_s:
                out.append(
                    ExposedCollective(
                        op=collective_base(start.op) or start.op,
                        start_name=start.name,
                        bytes=nbytes,
                        collective_time_s=wire_s,
                        overlap_compute_s=overlap_s,
                        comp=comp.name,
                    )
                )
    return out


# ------------------------------------------------------ fusion-break scan

@dataclasses.dataclass
class FusionBreak:
    """A kLoop fusion whose whole output round-trips HBM just to feed one
    other kLoop fusion — an elementwise chain XLA materialized mid-way."""

    producer: str
    consumer: str
    buffer_bytes: int
    comp: str

    @property
    def extra_hbm_bytes(self) -> int:
        return 2 * self.buffer_bytes  # one write + one read back


def find_fusion_breaks(text: str, *, min_bytes: int = 32 << 20) -> list[FusionBreak]:
    """Pairs of kLoop fusions where the producer's only consumer is the
    other fusion and the materialized intermediate is >= ``min_bytes``."""
    comps = parse_hlo_module(text)
    out: list[FusionBreak] = []
    for comp in comps.values():
        loop_fusions = {
            i.name: i
            for i in comp.instrs
            if i.op == "fusion" and "kind=kLoop" in i.attrs
        }
        if not loop_fusions:
            continue
        uses: dict[str, list[HloInstr]] = defaultdict(list)
        for instr in comp.instrs:
            for _, _, name in instr.operands:
                uses[name].append(instr)
        for name, producer in loop_fusions.items():
            if producer.out_bytes < min_bytes:
                continue
            consumers = uses.get(name, [])
            if len(consumers) == 1 and consumers[0].name in loop_fusions:
                out.append(
                    FusionBreak(
                        producer=name,
                        consumer=consumers[0].name,
                        buffer_bytes=producer.out_bytes,
                        comp=comp.name,
                    )
                )
    return out

"""ATX1xx — sharding-spec rules.

The GSPMD contract is that collective placement is fully determined by the
PartitionSpec annotations, which makes spec mistakes statically checkable —
and on TPU they MUST be caught statically, because the runtime failure mode
is silent replication (5-50x slower, 1/N of the memory story), not an error.
"""

from __future__ import annotations

from typing import Iterator

import numpy as np
from jax.sharding import PartitionSpec

from ..parallel.mesh import spec_entry_axes, unknown_spec_axes
from ..parallel.sharding import (
    ShardingStrategy,
    canonicalize_spec,
    infer_opt_specs,
)
from ..utils.dataclasses import ShardingStrategyType
from .engine import LintContext, _flat_with_paths, _is_spec, _leaf_bytes, rule
from .findings import Finding, Severity
from .hbm import human_bytes, state_hbm_per_device

_REPLICATED_KINDS = (
    ShardingStrategyType.DATA_PARALLEL,
    ShardingStrategyType.ZERO1,
    ShardingStrategyType.ZERO2,
)


@rule(
    "ATX101",
    Severity.WARNING,
    "sharding",
    "PartitionSpec entry dropped: dim not divisible by the mesh axis size",
    "pad the dim to a multiple of the axis-group size, shard a different "
    "dim, or pick a mesh whose axis divides it",
)
def atx101_indivisible_dims(ctx: LintContext) -> Iterator[Finding]:
    # Inference path: `infer_param_specs` already emits the structured
    # ShardingSpecWarning per drop; the context captured them.
    ctx.resolved_param_specs()
    for w in ctx.spec_warnings:
        yield Finding(
            "ATX101",
            Severity.WARNING,
            w.path,
            f"spec entry {w.entry!r} on dim {w.dim} (size {w.dim_size}) is "
            f"not divisible by mesh axes {list(w.axes)} (group {w.group}) — "
            "the dim silently replicates on every device",
            "pad the dim to a multiple of the axis-group size, shard a "
            "different dim, or pick a mesh whose axis divides it",
        )
    # Explicit-specs path: the caller handed in the spec tree, so check
    # divisibility directly (inference never ran, no warnings captured).
    if ctx.param_specs is None or ctx._inference_ran:
        return
    for path, leaf, spec in ctx.iter_spec_leaves("params"):
        shape = tuple(getattr(leaf, "shape", ()))
        for d, entry in enumerate(spec):
            axes = spec_entry_axes(entry)
            if not axes or any(a not in ctx.mesh.shape for a in axes):
                continue  # unknown axes are ATX102's finding
            group = int(np.prod([ctx.mesh.shape[a] for a in axes]))
            if group > 1 and d < len(shape) and shape[d] % group != 0:
                yield Finding(
                    "ATX101",
                    Severity.WARNING,
                    path,
                    f"spec entry {entry!r} on dim {d} (size {shape[d]}) is "
                    f"not divisible by mesh axes {list(axes)} (group "
                    f"{group}) — XLA pads/replicates instead of sharding",
                    "pad the dim to a multiple of the axis-group size, "
                    "shard a different dim, or resize the mesh axis",
                )


@rule(
    "ATX102",
    Severity.ERROR,
    "sharding",
    "PartitionSpec references an axis name the mesh does not define",
    "rename the spec axis to one of the mesh axes, or add the axis to "
    "MeshConfig / ATX_MESH_*",
)
def atx102_unknown_axes(ctx: LintContext) -> Iterator[Finding]:
    if ctx.mesh is None:
        return
    mesh_axes = tuple(ctx.mesh.axis_names)

    def check(spec: PartitionSpec, where: str) -> Iterator[Finding]:
        unknown = unknown_spec_axes(spec, ctx.mesh)
        if unknown:
            yield Finding(
                "ATX102",
                Severity.ERROR,
                where,
                f"spec {spec} references mesh axes {list(unknown)} that do "
                f"not exist (mesh axes: {mesh_axes}) — NamedSharding "
                "construction would fail with an opaque KeyError",
                f"rename the axis to one of {mesh_axes}, or add it to the "
                "mesh (MeshConfig / ATX_MESH_*)",
            )

    if ctx.strategy is not None:
        for pattern, spec in getattr(ctx.strategy, "rules", ()):
            yield from check(spec, f"rule {pattern!r}")
    for which in ("params", "opt"):
        explicit = ctx.param_specs if which == "params" else ctx.opt_specs
        if explicit is None:
            continue
        for path, spec in _flat_with_paths(explicit, is_leaf=_is_spec):
            yield from check(spec, path)


@rule(
    "ATX103",
    Severity.WARNING,
    "sharding",
    "large param fully replicated while the mesh has free sharding axes",
    "add a sharding rule for the param, lower FsdpPlugin.min_weight_size, "
    "or pad its dims so the fsdp axis divides one",
)
def atx103_large_replicated(ctx: LintContext) -> Iterator[Finding]:
    if ctx.mesh is None:
        return
    if ctx.strategy is not None and ctx.strategy.kind in _REPLICATED_KINDS:
        return  # replication is these strategies' contract, not a bug
    avail = [
        a for a in ctx.mesh.axis_names if a != "data" and ctx.mesh.shape[a] > 1
    ]
    if not avail:
        return
    threshold = ctx.opt("replicated_bytes_threshold")
    for path, leaf, spec in ctx.iter_spec_leaves("params"):
        nbytes = _leaf_bytes(leaf)
        if nbytes < threshold:
            continue
        try:
            canonical = canonicalize_spec(spec, ctx.mesh, path)
        except ValueError:
            continue  # unknown axes: ATX102 owns it
        if canonical == PartitionSpec():
            yield Finding(
                "ATX103",
                Severity.WARNING,
                path,
                f"{human_bytes(nbytes)} param is fully replicated although "
                f"mesh axes {avail} are available to shard it — every "
                "device holds (and all-reduces grads for) a full copy",
                "add a sharding rule matching this param, lower "
                "FsdpPlugin.min_weight_size, or pad an indivisible dim",
            )


@rule(
    "ATX105",
    Severity.INFO,
    "sharding",
    "per-device HBM accounting of the sharded train state",
)
def atx105_hbm_accounting(ctx: LintContext) -> Iterator[Finding]:
    if ctx.params_shapes is None or ctx.mesh is None:
        return
    param_specs = ctx.resolved_param_specs()
    if param_specs is None:
        return
    opt_specs = ctx.opt_specs
    if opt_specs is None and ctx.opt_shapes is not None:
        # The prepare() path hands in opt shapes only; account them under
        # the specs the framework would plan for them.
        strategy = ctx.strategy if ctx.strategy is not None else ShardingStrategy()
        try:
            opt_specs = infer_opt_specs(
                ctx.opt_shapes, ctx.params_shapes, param_specs, ctx.mesh, strategy
            )
        except Exception:
            opt_specs = None
    try:
        breakdown = state_hbm_per_device(
            ctx.params_shapes,
            param_specs,
            ctx.mesh,
            opt_shapes=ctx.opt_shapes,
            opt_specs=opt_specs,
        )
    except Exception:
        return
    # Cite the compiled-HLO timeline figure next to the first-order
    # arithmetic when one is buildable (function-level import: rules_memory
    # imports the engine, and ATX105 sorts before ATX701 so the shared
    # cached sweep is triggered here).
    compiled_note = ""
    data = None
    from .rules_memory import timeline_for

    timeline = timeline_for(ctx)
    if timeline is not None and timeline.peak_bytes > 0:
        compiled_note = (
            f" — compiled-HLO static peak {human_bytes(timeline.peak_bytes)}"
            f" (ATX701 timeline)"
        )
        data = {
            "first_order_total_bytes": breakdown.total,
            "compiled_peak_hbm_bytes": timeline.peak_bytes,
        }
    yield Finding(
        "ATX105",
        Severity.INFO,
        "",
        f"sharded train-state HBM: {breakdown.format()}{compiled_note}",
        "",
        data=data,
    )


@rule(
    "ATX104",
    Severity.WARNING,
    "sharding",
    "optimizer-state spec conflicts with the spec planned from its param",
    "derive optimizer-state specs with infer_opt_specs (or mirror the "
    "param specs) so moments live where their params live",
)
def atx104_param_opt_conflict(ctx: LintContext) -> Iterator[Finding]:
    if ctx.opt_specs is None or ctx.opt_shapes is None or ctx.params_shapes is None:
        return
    param_specs = ctx.resolved_param_specs()
    if param_specs is None or ctx.mesh is None:
        return
    strategy = ctx.strategy if ctx.strategy is not None else ShardingStrategy()
    try:
        expected = infer_opt_specs(
            ctx.opt_shapes, ctx.params_shapes, param_specs, ctx.mesh, strategy
        )
    except Exception:
        return
    expected_flat = _flat_with_paths(expected, is_leaf=_is_spec)
    actual_flat = _flat_with_paths(ctx.opt_specs, is_leaf=_is_spec)
    if len(expected_flat) != len(actual_flat):
        return
    for (path, exp), (_, act) in zip(expected_flat, actual_flat):
        try:
            if canonicalize_spec(exp, ctx.mesh) == canonicalize_spec(act, ctx.mesh):
                continue
        except ValueError:
            continue  # unknown axes: ATX102 owns it
        yield Finding(
            "ATX104",
            Severity.WARNING,
            path,
            f"optimizer-state spec {act} conflicts with the spec planned "
            f"from its parameter ({exp}) — XLA inserts a reshard of the "
            "moments on every step's update",
            "derive optimizer-state specs with infer_opt_specs (or mirror "
            "the param specs); only ZeRO-1 intentionally diverges",
        )

"""Simulated-process replay of a host-side loop: the ATX5xx data source.

The PR-4 bug class — host control flow that diverges across processes and
sends one rank into a collective its peers never issue — hangs a real pod
and is invisible to single-process tests. This module makes it visible
ahead of time, in the spirit of MPI deadlock verifiers (MUST/ISP
match-order checking) reduced to the JAX SPMD world:

`replay_host_loop(loop_fn, processes=N)` runs ``loop_fn`` once *per
simulated process*, with `jax.process_index`/`jax.process_count` patched,
the state singletons isolated, per-process env deltas applied, and every
owned collective entry point intercepted:

- the `ops/` host collectives (gather / reduce / broadcast /
  gather_object / broadcast_object_list — `pad_across_processes` routes
  through the patched `gather_object`),
- `ProcessState.wait_for_everyone` (the `multihost_utils`-style barrier),
- the checkpoint commit barrier in `resilience/commit.py`
  (mark_precommit / wait_for_precommit / commit_dir),
- the preemption flag reads in `resilience/preemption.py` (recorded as
  annotations so ATX502 can tie a divergence to the flag that caused it),
- jitted-fn dispatch identity (`jax.jit` products record which compiled
  function each process actually invoked, with the abstract call
  signature).

Each intercepted call appends a `HostEvent` (op kind, name, abstract
operand signature, small-integer value fingerprint, call-site stack) to
that process's ordered **collective log**. The ATX5xx rules in
`rules_multihost.py` then align the N logs and report the first
divergence with both stacks.

**Group semantics under sequential replay.** Processes run in index order
within a *round*; a collective's group result is assembled from the peer
operands recorded at the same log position — current-round operands for
peers that already ran, previous-round operands for peers that haven't.
Round 0 therefore resolves lower-index peers exactly and falls back to
the caller's own operand for the rest; the replay iterates (``max_rounds``,
default 3) until every process's event sequence is identical to its
previous round — a fixpoint that lets information flow "backwards"
(e.g. process 1 adopting process 0's or-reduced preemption flag).

What the model cannot see: real wall-clock interleaving, per-process file
I/O content (each simulated process writes into the same local
filesystem), device-level collectives inside compiled code (GSPMD's
problem, checked by ATX4xx), and host effects outside the patched entry
points. docs/static_analysis.md lists the limits.
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
import re
import traceback
from contextlib import contextmanager
from typing import Any, Callable, Iterator

_THIS_FILE = os.path.abspath(__file__)
_ADDR_RE = re.compile(r"0x[0-9a-fA-F]+")

# Event kinds that participate in cross-process schedule alignment. The
# rest (flag_read, flag_set, commit, precommit_wait, exit, error) are
# per-process annotations: legitimately asymmetric (proc-0-only commit) or
# metadata the rules consult (flag values for ATX502).
ALIGNED_KINDS = frozenset(
    {
        "gather",
        "reduce",
        "broadcast",
        "gather_object",
        "broadcast_object_list",
        "barrier",
        "precommit",
        "dispatch",
    }
)


def sanitize_signature(text: str) -> str:
    """Strip memory addresses from reprs (treedefs embed ``<function ... at
    0x7f..>`` for optax/lambda nodes, which differ per replay run)."""
    return _ADDR_RE.sub("0x…", text)


def tree_signature(tree: Any) -> str:
    """Abstract signature of a pytree: structure + per-leaf shape:dtype.
    Values never enter the signature — two processes passing different
    *numbers* through the same collective still align."""
    import jax

    def leaf_sig(x: Any) -> str:
        if hasattr(x, "shape") and hasattr(x, "dtype"):
            return f"{tuple(x.shape)}:{x.dtype}"
        return type(x).__name__

    try:
        structure = jax.tree.structure(tree)
        leaves = [leaf_sig(leaf) for leaf in jax.tree.leaves(tree)]
        return sanitize_signature(f"{structure}|{leaves}")
    except Exception:
        return sanitize_signature(type(tree).__name__)


def tree_fingerprint(tree: Any) -> str:
    """Value hash of the *small integer* leaves only (flags, counters,
    uint32 PRNG keys — the things host control flow branches on and ATX504
    compares). Floats and big tensors are excluded so numeric churn never
    breaks the replay fixpoint."""
    import jax
    import numpy as np

    h = hashlib.sha1()
    found = False
    for leaf in jax.tree.leaves(tree):
        try:
            arr = np.asarray(leaf)
        except Exception:
            continue
        if arr.dtype.kind in "iub" and arr.nbytes <= 1024:
            h.update(str(arr.shape).encode())
            h.update(arr.tobytes())
            found = True
    return h.hexdigest()[:12] if found else ""


def _capture_stack(limit: int = 6) -> str:
    frames = traceback.extract_stack()
    keep = [
        f
        for f in frames
        if os.path.abspath(f.filename) != _THIS_FILE
        and "contextlib" not in os.path.basename(f.filename)
    ]
    return "".join(traceback.format_list(keep[-limit:])).rstrip()


@dataclasses.dataclass(frozen=True)
class HostEvent:
    """One intercepted host-side event in a simulated process's log."""

    kind: str  # gather/reduce/broadcast/.../barrier/precommit/dispatch/...
    name: str  # op detail: reduction kind, barrier name, jitted fn name
    signature: str  # abstract operand signature (sanitized)
    fingerprint: str  # value hash of small integer leaves ("" if none)
    stack: str  # formatted user call stack
    process: int
    index: int  # position in this process's full log
    collective: bool = True  # participates in schedule alignment
    cpos: int = -1  # position among this process's COLLECTIVE events

    @property
    def key(self) -> tuple[str, str, str]:
        """Alignment identity: what must agree across processes."""
        return (self.kind, self.name, self.signature)

    def describe(self) -> str:
        sig = self.signature
        if len(sig) > 120:
            sig = sig[:117] + "..."
        return f"{self.kind}:{self.name}" + (f" {sig}" if sig else "")


@dataclasses.dataclass
class HostTraceResult:
    """The aligned input to the ATX5xx rules: one ordered log per process."""

    logs: dict[int, list[HostEvent]]
    processes: int
    rounds: int
    converged: bool
    errors: dict[int, str] = dataclasses.field(default_factory=dict)

    def collectives(self, process: int) -> list[HostEvent]:
        """The alignment-relevant subsequence of one process's log."""
        return [e for e in self.logs.get(process, []) if e.collective]

    def annotations(self, process: int) -> list[HostEvent]:
        return [e for e in self.logs.get(process, []) if not e.collective]


class _SimWorld:
    """Cross-round state: recorders from the current and previous round."""

    def __init__(self, processes: int) -> None:
        self.processes = processes
        self.current: dict[int, "_Recorder"] = {}
        self.previous: dict[int, "_Recorder"] = {}

    def peer(self, q: int) -> "_Recorder | None":
        # Within a round processes run in index order, so a lower-index
        # peer's current-round log exists by the time a higher-index
        # process asks; higher-index peers resolve from the previous round.
        return self.current.get(q) or self.previous.get(q)


class _Recorder:
    """Per-(round, process) collective log + the sim's preemption flag."""

    def __init__(self, world: _SimWorld, process: int, preempted: bool) -> None:
        self.world = world
        self.process = process
        self.preempted = preempted
        self.events: list[HostEvent] = []
        self.collective_events: list[HostEvent] = []
        self.operands: dict[int, Any] = {}
        self.error: str | None = None

    def record(
        self,
        kind: str,
        name: str,
        tree: Any = None,
        *,
        signature: str | None = None,
        fingerprint: str | None = None,
        collective: bool | None = None,
    ) -> HostEvent:
        index = len(self.events)
        if signature is None:
            signature = tree_signature(tree) if tree is not None else ""
        if fingerprint is None:
            fingerprint = tree_fingerprint(tree) if tree is not None else ""
        if collective is None:
            collective = kind in ALIGNED_KINDS
        event = HostEvent(
            kind=kind,
            name=name,
            signature=signature,
            fingerprint=fingerprint,
            stack=_capture_stack(),
            process=self.process,
            index=index,
            collective=collective,
            cpos=len(self.collective_events) if collective else -1,
        )
        self.events.append(event)
        if collective:
            self.collective_events.append(event)
        if tree is not None:
            self.operands[index] = tree
        return event

    def peer_operand(self, own_event: HostEvent, q: int) -> Any | None:
        """Peer q's operand at the same *collective* position — per-process
        annotations (flag reads, proc-0-only commits) shift full-log
        indices, so alignment is by position in the collective subsequence.
        Only a peer whose event there has the same alignment key
        contributes (a diverged peer yields None; the caller falls back to
        its own operand)."""
        rec = self.world.peer(q)
        if rec is None or own_event.cpos < 0:
            return None
        if own_event.cpos >= len(rec.collective_events):
            return None
        peer_event = rec.collective_events[own_event.cpos]
        if peer_event.key != own_event.key:
            return None
        return rec.operands.get(peer_event.index)

    def group_operands(self, own_event: HostEvent, own_tree: Any) -> list[Any]:
        out: list[Any] = []
        for q in range(self.world.processes):
            if q == self.process:
                out.append(own_tree)
            else:
                peer = self.peer_operand(own_event, q)
                out.append(own_tree if peer is None else peer)
        return out


_ACTIVE_RECORDER: _Recorder | None = None


# ------------------------------------------------------------- collective stubs
def _stub_gather(rec: _Recorder) -> Callable:
    import jax
    import numpy as np

    def gather(tree: Any) -> Any:
        event = rec.record("gather", "gather", tree)
        trees = rec.group_operands(event, tree)
        try:
            return jax.tree.map(
                lambda *xs: np.concatenate(
                    [np.atleast_1d(np.asarray(x)) for x in xs], axis=0
                ),
                *trees,
            )
        except Exception:
            return jax.tree.map(
                lambda x: np.concatenate(
                    [np.atleast_1d(np.asarray(x))] * rec.world.processes, axis=0
                ),
                tree,
            )

    return gather


def _stub_reduce(rec: _Recorder) -> Callable:
    import jax
    import numpy as np

    def reduce(tree: Any, reduction: str = "mean") -> Any:
        if reduction == "none":
            return tree
        event = rec.record("reduce", f"reduce[{reduction}]", tree)
        trees = rec.group_operands(event, tree)

        def _combine(*xs: Any) -> Any:
            arrs = [np.asarray(x) for x in xs]
            out = arrs[0].astype(np.float64, copy=True)
            for a in arrs[1:]:
                out = out + a
            if reduction == "mean":
                out = out / len(arrs)
            return out.astype(arrs[0].dtype)

        try:
            return jax.tree.map(_combine, *trees)
        except Exception:
            return jax.tree.map(
                lambda x: (
                    np.asarray(x)
                    if reduction == "mean"
                    else np.asarray(x) * rec.world.processes
                ).astype(np.asarray(x).dtype),
                tree,
            )

    return reduce


def _stub_broadcast(rec: _Recorder) -> Callable:
    import jax
    import numpy as np

    def broadcast(tree: Any, from_process: int = 0) -> Any:
        event = rec.record("broadcast", f"broadcast[from={from_process}]", tree)
        src = (
            tree
            if from_process == rec.process
            else rec.peer_operand(event, from_process)
        )
        chosen = tree if src is None else src
        try:
            return jax.tree.map(lambda x: np.asarray(x).copy(), chosen)
        except Exception:
            return chosen

    return broadcast


def _stub_gather_object(rec: _Recorder) -> Callable:
    def gather_object(objects: list[Any]) -> list[Any]:
        # Object channels carry control metadata of per-process shape (the
        # source broadcasts a payload, peers pass templates/None), so only
        # the element COUNT enters the alignment signature.
        event = rec.record(
            "gather_object",
            "gather_object",
            signature=f"objects[{len(objects)}]",
        )
        rec.operands[event.index] = list(objects)
        out: list[Any] = []
        for q in range(rec.world.processes):
            if q == rec.process:
                out.extend(objects)
            else:
                peer = rec.peer_operand(event, q)
                out.extend(list(objects) if peer is None else peer)
        return out

    return gather_object


def _stub_broadcast_object_list(rec: _Recorder) -> Callable:
    def broadcast_object_list(objects: list[Any], from_process: int = 0) -> list[Any]:
        event = rec.record(
            "broadcast_object_list",
            f"broadcast_object_list[from={from_process}]",
            signature=f"objects[{len(objects)}]",
        )
        rec.operands[event.index] = list(objects)
        if from_process == rec.process:
            return list(objects)
        peer = rec.peer_operand(event, from_process)
        return list(objects) if peer is None else list(peer)

    return broadcast_object_list


def _stub_wait_for_everyone(rec: _Recorder) -> Callable:
    def wait_for_everyone(self) -> None:  # bound as a ProcessState method
        rec.record("barrier", "wait_for_everyone")

    return wait_for_everyone


def _stub_mark_precommit(rec: _Recorder, real: Callable) -> Callable:
    def mark_precommit(tmp_dir: str, proc: int) -> None:
        rec.record("precommit", "mark_precommit")
        real(tmp_dir, proc)

    return mark_precommit


def _stub_wait_for_precommit(rec: _Recorder) -> Callable:
    def wait_for_precommit(
        tmp_dir: str, num_processes: int, timeout_secs: float
    ) -> None:
        # Proc-0-only annotation; never actually waits (peers run later in
        # the same round). Clean up any markers the real mark_precommit
        # wrote so they don't land in the committed directory.
        rec.record("precommit_wait", "wait_for_precommit", collective=False)
        from ..resilience.commit import PRECOMMIT_FILE

        for p in range(num_processes):
            try:
                os.remove(os.path.join(tmp_dir, PRECOMMIT_FILE.format(proc=p)))
            except OSError:
                pass

    return wait_for_precommit


def _stub_commit_dir(rec: _Recorder, real: Callable) -> Callable:
    def commit_dir(tmp_dir: str, final_dir: str, meta: Any = None) -> None:
        rec.record("commit", "commit_dir", collective=False)
        real(tmp_dir, final_dir, meta)

    return commit_dir


def _stub_preemption(rec: _Recorder) -> tuple[Callable, Callable, Callable]:
    def preemption_requested() -> bool:
        rec.record(
            "flag_read",
            "preemption_requested",
            fingerprint=str(int(rec.preempted)),
            collective=False,
        )
        return rec.preempted

    def request_preemption() -> None:
        rec.record("flag_set", "request_preemption", collective=False)
        rec.preempted = True

    def clear_preemption() -> None:
        rec.preempted = False

    return preemption_requested, request_preemption, clear_preemption


class _DispatchRecorder:
    """Wraps a `jax.jit` product: records which compiled function each
    simulated process dispatches (and on what abstract signature), then
    calls through. Attribute access (``.lower``, ``.trace`` …) passes
    through so the wrapper stays a drop-in jitted callable."""

    def __init__(self, jitted: Callable, name: str) -> None:
        object.__setattr__(self, "_atx_jitted", jitted)
        object.__setattr__(self, "_atx_name", name)

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        rec = _ACTIVE_RECORDER
        if rec is not None:
            rec.record(
                "dispatch",
                self._atx_name,
                signature=tree_signature((args, kwargs)),
            )
        return self._atx_jitted(*args, **kwargs)

    def __getattr__(self, name: str) -> Any:
        return getattr(object.__getattribute__(self, "_atx_jitted"), name)


def _patched_jit(orig_jit: Callable) -> Callable:
    def jit(fn: Callable | None = None, *args: Any, **kwargs: Any) -> Any:
        if fn is None:

            def deco(f: Callable) -> Any:
                return jit(f, *args, **kwargs)

            return deco
        jitted = orig_jit(fn, *args, **kwargs)
        return _DispatchRecorder(jitted, getattr(fn, "__name__", "jitted"))

    return jit


# -------------------------------------------------------------------- patching
@contextmanager
def simulated_process(
    process: int, process_count: int, env: dict[str, str] | None = None
) -> Iterator[None]:
    """Impersonate one SPMD process: patch `jax.process_index`/`process_count`
    (safe — jax internals resolve theirs through `jax._src.xla_bridge`,
    only user/host code sees the patch), isolate the shared-``__dict__``
    state singletons, and apply env deltas. Restores everything on exit."""
    import jax

    from .. import state as _state

    deltas = {"ATX_PREEMPTION_HANDLER": "0", **(env or {})}
    saved_env: dict[str, str | None] = {}
    for key, value in deltas.items():
        saved_env[key] = os.environ.get(key)
        os.environ[key] = value

    orig_pi, orig_pc = jax.process_index, jax.process_count
    jax.process_index = lambda backend=None: process
    jax.process_count = lambda backend=None: process_count

    singletons = (
        _state.ProcessState,
        _state.AcceleratorState,
        _state.GradientState,
    )
    # The shared dict IS every instance's __dict__ — save/restore its
    # CONTENTS, never swap the dict object.
    saved_states = [(cls, dict(cls._shared_state)) for cls in singletons]
    for cls in singletons:
        cls._shared_state.clear()
    try:
        yield
    finally:
        for cls, saved in saved_states:
            cls._shared_state.clear()
            cls._shared_state.update(saved)
        jax.process_index, jax.process_count = orig_pi, orig_pc
        for key, value in saved_env.items():
            if value is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = value


@contextmanager
def _patched_world(rec: _Recorder) -> Iterator[None]:
    """Swap every owned collective entry point for the recorder's stubs.
    Patches module attrs AND every by-value re-export site (`ops` package,
    `resilience` package, the top-level `accelerate_tpu` namespace), so
    `from .ops import collectives as _ops` and `resilience.request_preemption`
    style call sites all land on the stubs."""
    global _ACTIVE_RECORDER

    import jax

    import accelerate_tpu as _pkg

    from .. import ops as _ops_pkg
    from .. import resilience as _res_pkg
    from ..ops import collectives as _coll
    from ..resilience import commit as _commit
    from ..resilience import preemption as _pre
    from ..state import ProcessState

    pre_req, pre_set, pre_clear = _stub_preemption(rec)
    replacements: dict[str, Callable] = {
        "gather": _stub_gather(rec),
        "reduce": _stub_reduce(rec),
        "broadcast": _stub_broadcast(rec),
        "gather_object": _stub_gather_object(rec),
        "broadcast_object_list": _stub_broadcast_object_list(rec),
    }
    commit_replacements: dict[str, Callable] = {
        "mark_precommit": _stub_mark_precommit(rec, _commit.mark_precommit),
        "wait_for_precommit": _stub_wait_for_precommit(rec),
        "commit_dir": _stub_commit_dir(rec, _commit.commit_dir),
    }
    pre_replacements: dict[str, Callable] = {
        "preemption_requested": pre_req,
        "request_preemption": pre_set,
        "clear_preemption": pre_clear,
    }

    patches: list[tuple[Any, str, Any]] = []

    def patch(obj: Any, name: str, value: Any) -> None:
        if hasattr(obj, name):
            patches.append((obj, name, getattr(obj, name)))
            setattr(obj, name, value)

    for name, value in replacements.items():
        patch(_coll, name, value)
        patch(_ops_pkg, name, value)
    for name, value in commit_replacements.items():
        patch(_commit, name, value)
        patch(_res_pkg, name, value)
    for name, value in pre_replacements.items():
        patch(_pre, name, value)
        patch(_res_pkg, name, value)
        patch(_pkg, name, value)
    patch(ProcessState, "wait_for_everyone", _stub_wait_for_everyone(rec))
    patch(jax, "jit", _patched_jit(jax.jit))
    # Async checkpoint saves: the real _AsyncSaver runs the shard write +
    # precommit barrier on a worker thread, which the sequential replay
    # cannot interleave. The stub records the submission as an annotation
    # and runs the job INLINE, so the job's own mark_precommit /
    # wait_for_precommit calls land in this process's collective log in
    # submission order — exactly the schedule the async file-barrier
    # produces (every process submits at the same step).
    from .. import checkpointing as _ckpt

    class _SyncSaverStub:
        def submit(self, fn: Callable, *args: Any) -> None:
            rec.record("async_submit", "async_save", collective=False)
            fn(*args)

        def wait(self) -> None:
            pass

    patch(_ckpt, "_ASYNC_SAVER", _SyncSaverStub())

    prev_recorder = _ACTIVE_RECORDER
    _ACTIVE_RECORDER = rec
    try:
        yield
    finally:
        _ACTIVE_RECORDER = prev_recorder
        for obj, name, orig in reversed(patches):
            setattr(obj, name, orig)


# ---------------------------------------------------------------------- replay
def _env_for(env: Any, process: int) -> dict[str, str] | None:
    if not env:
        return None
    if all(isinstance(k, int) for k in env):
        return env.get(process)
    return env


def _logs_equal(a: dict[int, "_Recorder"], b: dict[int, "_Recorder"]) -> bool:
    if set(a) != set(b):
        return False
    for p in a:
        ea = [(e.kind, e.name, e.signature, e.fingerprint) for e in a[p].events]
        eb = [(e.kind, e.name, e.signature, e.fingerprint) for e in b[p].events]
        if ea != eb:
            return False
    return True


def replay_host_loop(
    loop_fn: Callable[[], Any],
    *,
    processes: int = 2,
    env: dict[str, str] | dict[int, dict[str, str]] | None = None,
    preempted: Any = (),
    max_rounds: int = 3,
) -> HostTraceResult:
    """Run ``loop_fn`` once per simulated process (per round) and return the
    per-process collective logs.

    ``env`` is either a common env-delta dict or ``{process: {...}}``.
    ``preempted`` lists simulated process indices whose preemption flag
    starts set (the SIGTERM-skew scenario ATX502 exists for).
    ``SystemExit`` from the loop is part of the preemption protocol and is
    recorded, not raised; other exceptions are recorded as annotations and
    reported via ``result.errors``.
    """
    if processes < 2:
        raise ValueError("replay_host_loop needs processes >= 2")
    world = _SimWorld(processes)
    preempted_set = set(preempted)
    converged = False
    rounds = 0
    for r in range(max_rounds):
        rounds = r + 1
        world.previous, world.current = world.current, {}
        for p in range(processes):
            rec = _Recorder(world, p, preempted=p in preempted_set)
            with simulated_process(p, processes, env=_env_for(env, p)):
                with _patched_world(rec):
                    try:
                        loop_fn()
                    except SystemExit as e:
                        rec.record(
                            "exit",
                            f"SystemExit({e.code})",
                            collective=False,
                        )
                    except Exception as e:
                        rec.error = f"{type(e).__name__}: {e}"
                        rec.record(
                            "error", f"{type(e).__name__}: {e}", collective=False
                        )
            world.current[p] = rec
        if world.previous and _logs_equal(world.previous, world.current):
            converged = True
            break
    return HostTraceResult(
        logs={p: world.current[p].events for p in range(processes)},
        processes=processes,
        rounds=rounds,
        converged=converged,
        errors={
            p: world.current[p].error
            for p in range(processes)
            if world.current[p].error
        },
    )

"""Static per-device HBM lifetime analysis over compiled (scheduled) HLO.

The ATX6xx roofline bounds *compute* ahead of time; this module bounds
*memory* the same way. The optimized HLO `LintContext.compiled_text()`
resolves is **scheduled** (`is_scheduled=true` in the module header), so
the entry computation's instruction order is the order XLA's buffer
assigner allocates against — which makes peak live bytes statically
computable on the CPU container, with zero buffers materialized:

- every entry instruction defines a buffer of its result bytes; bookkeeping
  ops (`bitcast` / `tuple` / `get-tuple-element` / `*-done`) alias existing
  buffers and define nothing;
- a buffer is live from its defining instruction through its last use;
  entry **parameters** are caller-owned and live for the whole program —
  donation shows up as `input_output_alias={ {k}: (p, ...) }` entries in
  the module header, which let output producers write into the donated
  parameter's storage instead of allocating fresh bytes (the 2x-state
  credit ATX201 reasons about);
- `while` results run in place over their carried operand; the loop
  **body**'s internal buffers are charged at the while's schedule position
  (carries stay resident across iterations), computed by recursing the
  same sweep; **fusion** temporaries stay on-chip and collapse to the
  fusion's materialized output;
- every buffer is attributed to a category — params / grads+opt-state /
  serving KV rows (from the abstract-arg tree path jax embeds in each
  parameter's ``op_name`` metadata), other inputs, collective scratch,
  XLA temps (layout/precision copies), or activations.

The result is a `MemoryTimeline`: the full live-bytes series over the
schedule, the peak, the instruction at the peak, and per-category
attribution at the peak — cross-checkable against the executable's own
`compiled.memory_analysis()` totals (`cross_check`). The ATX7xx rules
(`analysis/rules_memory.py`) and the serving capacity planner
(`analysis/capacity.py`) consume it.

Model limits (docs/static_analysis.md): liveness is tracked at
whole-value granularity against the schedule, so in-place reuse the
buffer assigner finds *between* differently-shaped values is not modeled
(the static peak is an upper bound over assignable layouts, not a
bit-exact replay of the assignment), and `conditional` sites are charged
at their branches' internal peak regardless of which branch runs.
"""

from __future__ import annotations

import dataclasses
import re
from collections import defaultdict
from typing import Any

from .roofline import (
    HloComputation,
    _CALLED_RE,
    collective_base,
    entry_computation,
    parse_hlo_module,
)

__all__ = [
    "Buffer",
    "MemoryTimeline",
    "build_timeline",
    "classify_param_path",
    "parse_input_output_aliases",
]

# Ops that alias an existing buffer instead of defining a new one. A
# `-done` completes the async op whose `-start` allocated the result, and
# a `while` runs in place over its carried operand.
_ALIAS_OPS = frozenset({
    "bitcast", "get-tuple-element", "tuple", "after-all", "add-dependency",
    "opt-barrier", "domain", "while",
})
# Buffers defined purely to change layout/precision/extent — XLA temps,
# not model state or activations (a materialized upcast lands here).
_TEMP_OPS = frozenset({
    "copy", "convert", "transpose", "reshape", "pad", "broadcast",
})

_PARAM_NUM_RE = re.compile(r"%?([\w.\-]+)\s*=\s*[^=\n]*?parameter\((\d+)\)")
_ROOT_RE = re.compile(r"ROOT\s+%?([\w.\-]+)")
_ALIAS_ENTRY_RE = re.compile(r"\{\s*[0-9,\s]*\}\s*:\s*\((\d+)")

# Tree-path tokens -> category, checked in order: an optimizer moment tree
# mirrors the param tree ("opt_state.mu['layers_0']['wq']"), so the
# opt-state check must win over a nested params token, and a KV cache is
# often nested under neither.
_KV_TOKENS = frozenset({"kv", "cache", "kv_cache", "k_cache", "v_cache"})
_OPT_TOKENS = frozenset({
    "opt_state", "opt", "mu", "nu", "grads", "grad", "loss_scale",
    "momentum", "v_row", "v_col", "exp_avg", "exp_avg_sq",
})
_PARAM_TOKENS = frozenset({"params", "param", "weights"})


def classify_param_path(path: str) -> str:
    """Category for an entry parameter from its abstract-arg tree path (the
    ``op_name`` metadata jax stamps on entry parameters — e.g.
    ``state['params']['wq']``, with quotes escaped in the HLO text)."""
    tokens = set(re.split(r"[^a-z0-9_]+", path.lower())) - {""}
    if tokens & _KV_TOKENS:
        return "kv"
    if tokens & _OPT_TOKENS:
        return "opt_state"
    if tokens & _PARAM_TOKENS:
        return "params"
    return "inputs"


def parse_input_output_aliases(text: str) -> list[int]:
    """Donated parameter numbers from the module header's
    ``input_output_alias={ {k}: (p, {}, may-alias), ... }`` — the compiled
    form `donate_argnums` resolves to."""
    marker = "input_output_alias={"
    start = text.find(marker)
    if start < 0:
        return []
    i, depth = start + len(marker) - 1, 0
    while i < len(text):
        if text[i] == "{":
            depth += 1
        elif text[i] == "}":
            depth -= 1
            if depth == 0:
                break
        i += 1
    body = text[start + len(marker) : i]
    return [int(m.group(1)) for m in _ALIAS_ENTRY_RE.finditer(body)]


@dataclasses.dataclass
class Buffer:
    """One statically-tracked HBM buffer in the entry schedule."""

    name: str
    op: str
    bytes: int           # fresh bytes this buffer allocates (reduced when
                         # it writes into donated parameter storage)
    category: str        # params / opt_state / kv / inputs / activations /
                         # collective / xla_temp
    def_index: int
    first_use: int       # -1 when never read
    last_use: int        # schedule index; == n_instructions for buffers
                         # that survive the program (params, outputs)
    dtype: str = ""
    shape: tuple[int, ...] = ()
    param_number: int = -1
    path: str = ""       # abstract-arg tree path (parameters only)
    donated: bool = False
    is_output: bool = False


@dataclasses.dataclass
class MemoryTimeline:
    """Static per-device HBM timeline of one compiled module."""

    peak_bytes: int
    peak_index: int
    peak_instr: str            # "name (op)" of the instruction at the peak
    categories_at_peak: dict[str, int]
    series: list[tuple[int, int]]   # (schedule index, live bytes)
    buffers: list[Buffer]
    n_instructions: int
    argument_bytes: int        # all entry parameters, donated included
    output_bytes: int          # full output tuple, aliased elements included
    alias_bytes: int           # donated-parameter bytes credited back
    max_working_set_bytes: int  # largest single-instruction operands+output
    output_signatures: list[tuple[str, tuple[int, ...]]]

    @property
    def peak_mib(self) -> float:
        return self.peak_bytes / 2**20

    def live_at_peak(self) -> list[Buffer]:
        i = self.peak_index
        return [b for b in self.buffers if b.def_index <= i <= b.last_use]

    def downsampled_series(self, max_points: int = 256) -> list[list[int]]:
        """The timeline as ``[index, live_bytes]`` pairs, thinned to at most
        ~``max_points`` (the peak always kept) — the `--json` payload."""
        if len(self.series) <= max_points:
            return [[i, b] for i, b in self.series]
        stride = -(-len(self.series) // max_points)
        return [
            [i, b] for j, (i, b) in enumerate(self.series)
            if j % stride == 0 or i == self.peak_index
        ]

    def cross_check(self, stats: Any) -> dict[str, float]:
        """Relative disagreement vs the executable's own
        `compiled.memory_analysis()` (CompiledMemoryStats) on the totals
        both sides define. The executable reports argument bytes over ALL
        parameters (donated included), output bytes over the FULL output
        tuple (aliased elements included, plus a pointer-table overhead of
        a few words), and alias bytes as the donated-parameter total — the
        same conventions used here. Keys absent when a stat is unreported
        (zero)."""
        out: dict[str, float] = {}
        for key, ours, attr in (
            ("argument_rel_err", self.argument_bytes, "argument_size_in_bytes"),
            ("output_rel_err", self.output_bytes, "output_size_in_bytes"),
            ("alias_rel_err", self.alias_bytes, "alias_size_in_bytes"),
        ):
            theirs = int(getattr(stats, attr, 0) or 0)
            if theirs > 0:
                out[key] = abs(ours - theirs) / theirs
        return out


def _alias_roots(comp: HloComputation) -> dict[str, frozenset[str]]:
    """name -> defining-buffer names, resolved through bookkeeping ops."""
    memo: dict[str, frozenset[str]] = {}

    def roots(name: str) -> frozenset[str]:
        cached = memo.get(name)
        if cached is not None:
            return cached
        memo[name] = frozenset()  # cycle guard
        instr = comp.by_name.get(name)
        if instr is None:
            result = frozenset()
        elif instr.op in _ALIAS_OPS or instr.op.endswith("-done"):
            merged: frozenset[str] = frozenset()
            for _, _, op_name in instr.operands:
                merged |= roots(op_name)
            result = merged or frozenset({name})
        else:
            result = frozenset({name})
        memo[name] = result
        return result

    for instr in comp.instrs:
        roots(instr.name)
    return memo


def _control_flow_sites(instr: Any) -> list[str]:
    """Called computations whose internal buffers stay resident while the
    op runs. Fusion temps collapse on-chip; loop conditions and scalar
    reduce/collective regions are negligible."""
    if instr.op == "fusion":
        return []
    sites = []
    for m in _CALLED_RE.finditer(instr.attrs):
        kind, target = m.group("kind"), m.group(2).strip("%{} ")
        if kind in ("body", "true_computation", "false_computation",
                    "branch_computations") or (
            kind == "calls" and instr.op == "call"
        ):
            sites.append(target)
    return sites


def _categorize(instr: Any) -> str:
    if collective_base(instr.op) or instr.op.endswith("-start"):
        return "collective"
    if instr.op in _TEMP_OPS:
        return "xla_temp"
    return "activations"


def _internal_peak(
    comps: dict[str, HloComputation],
    comp_name: str,
    memo: dict[str, int],
    visiting: set[str],
) -> int:
    """Peak of the buffers a called computation (while body / call /
    conditional branch) holds internally, charged at the call site's
    schedule position. Its parameters alias the carried operands already
    counted at the site (0 fresh bytes); buffers feeding its root stay
    live to the end of the body — the across-iterations residency."""
    if comp_name in memo:
        return memo[comp_name]
    if comp_name in visiting:
        return 0
    comp = comps.get(comp_name)
    if comp is None or not comp.instrs:
        return 0
    visiting.add(comp_name)

    roots_map = _alias_roots(comp)
    uses: dict[str, list[int]] = defaultdict(list)
    for instr in comp.instrs:
        for _, _, op_name in instr.operands:
            for root in roots_map.get(op_name, ()):
                uses[root].append(instr.index)
    n = len(comp.instrs)
    output_roots = roots_map.get(comp.instrs[-1].name, frozenset())

    delta = [0] * (n + 2)
    extra_at: dict[int, int] = {}
    for instr in comp.instrs:
        for target in _control_flow_sites(instr):
            extra_at[instr.index] = extra_at.get(instr.index, 0) + _internal_peak(
                comps, target, memo, visiting
            )
        if (
            instr.op in _ALIAS_OPS
            or instr.op.endswith("-done")
            or instr.op == "parameter"
        ):
            continue
        last = n if instr.name in output_roots else max(
            uses.get(instr.name, []), default=instr.index
        )
        delta[instr.index] += instr.out_bytes
        delta[min(last, n) + 1] -= instr.out_bytes

    live, peak = 0, 0
    for i in range(n):
        live += delta[i]
        peak = max(peak, live + extra_at.get(i, 0))
    visiting.discard(comp_name)
    memo[comp_name] = peak
    return peak


def build_timeline(
    text: str,
    *,
    param_paths: dict[int, str] | None = None,
) -> MemoryTimeline | None:
    """Build the static HBM timeline for one compiled module's entry
    computation. ``param_paths`` maps entry parameter numbers to
    abstract-arg tree paths — the fallback when the HLO's ``op_name``
    metadata was stripped. None when the text has no entry computation."""
    comps = parse_hlo_module(text)
    entry = entry_computation(comps)
    if entry is None or not entry.instrs:
        return None
    n = len(entry.instrs)
    donated = frozenset(parse_input_output_aliases(text))
    # Instruction names are module-unique: keep param numbers only for
    # names that are entry parameters (nested computations number their
    # own parameters from 0 too).
    param_numbers = {
        name: int(num)
        for name, num in _PARAM_NUM_RE.findall(text)
        if name in entry.by_name and entry.by_name[name].op == "parameter"
    }

    roots_map = _alias_roots(entry)
    uses: dict[str, list[int]] = defaultdict(list)
    for instr in entry.instrs:
        for _, _, op_name in instr.operands:
            for root in roots_map.get(op_name, ()):
                uses[root].append(instr.index)

    root_name = next(
        (r for r in _ROOT_RE.findall(text) if r in entry.by_name),
        entry.instrs[-1].name,
    )
    root_instr = entry.by_name[root_name]
    output_roots = (
        roots_map.get(root_name, frozenset())
        if root_instr.op != "parameter"
        else frozenset({root_name})
    )

    buffers: list[Buffer] = []
    param_bytes: dict[int, int] = {}
    for instr in entry.instrs:
        if instr.op in _ALIAS_OPS or instr.op.endswith("-done"):
            continue
        use_list = uses.get(instr.name, [])
        if instr.op == "parameter":
            num = param_numbers.get(instr.name, -1)
            path = instr.op_name or (param_paths or {}).get(num, "")
            buf = Buffer(
                name=instr.name,
                op=instr.op,
                bytes=instr.out_bytes,
                category=classify_param_path(path) if path else "inputs",
                def_index=0,
                first_use=min(use_list, default=-1),
                last_use=n,  # caller-owned: live for the whole program
                dtype=instr.dtype,
                shape=tuple(instr.shape),
                param_number=num,
                path=path,
                donated=num in donated,
                is_output=instr.name in output_roots,
            )
            if num >= 0:
                param_bytes[num] = instr.out_bytes
        else:
            is_out = instr.name in output_roots
            last = n if is_out else max(use_list, default=instr.index)
            buf = Buffer(
                name=instr.name,
                op=instr.op,
                bytes=instr.out_bytes,
                category=_categorize(instr),
                def_index=instr.index,
                first_use=min(use_list, default=-1),
                last_use=last,
                dtype=instr.dtype,
                shape=tuple(instr.shape),
                is_output=is_out,
            )
        buffers.append(buf)

    # Donation credit: producers of aliased output elements write into the
    # donated parameters' storage — their fresh bytes shrink by the donated
    # total. Which producer lands in which tuple element is immaterial for
    # the timeline totals, so the credit drains largest-producer-first.
    alias_bytes = sum(param_bytes.get(p, 0) for p in donated)
    credit = alias_bytes
    for buf in sorted(
        (b for b in buffers if b.is_output and b.param_number < 0),
        key=lambda b: -b.bytes,
    ):
        if credit <= 0:
            break
        taken = min(buf.bytes, credit)
        buf.bytes -= taken
        credit -= taken

    # Callee residency at control-flow sites (while bodies, calls).
    memo: dict[str, int] = {}
    extra_at: dict[int, int] = {}
    for instr in entry.instrs:
        for target in _control_flow_sites(instr):
            extra_at[instr.index] = extra_at.get(instr.index, 0) + _internal_peak(
                comps, target, memo, {entry.name}
            )

    delta = [0] * (n + 2)
    for buf in buffers:
        delta[min(buf.def_index, n)] += buf.bytes
        delta[min(buf.last_use, n) + 1] -= buf.bytes
    series: list[tuple[int, int]] = []
    live, peak, peak_index = 0, -1, 0
    for i in range(n):
        live += delta[i]
        total = live + extra_at.get(i, 0)
        series.append((i, total))
        if total > peak:
            peak, peak_index = total, i

    peak_i = entry.instrs[peak_index]
    cats: dict[str, int] = defaultdict(int)
    for b in buffers:
        if b.def_index <= peak_index <= b.last_use and b.bytes:
            cats[b.category] += b.bytes
    if extra_at.get(peak_index, 0):
        cats["activations"] += extra_at[peak_index]

    out_sigs: list[tuple[str, tuple[int, ...]]] = []
    if root_instr.op == "tuple":
        for dt, shape, name in root_instr.operands:
            src = entry.by_name.get(name)
            if src is not None and not dt:
                dt, shape = src.dtype, src.shape
            out_sigs.append((dt, tuple(shape)))
    else:
        out_sigs.append((root_instr.dtype, tuple(root_instr.shape)))

    max_ws = max(
        (
            i.operand_bytes + i.out_bytes
            for i in entry.instrs
            if i.op not in _ALIAS_OPS and i.op != "parameter"
        ),
        default=0,
    )

    return MemoryTimeline(
        peak_bytes=max(peak, 0),
        peak_index=peak_index,
        peak_instr=f"{peak_i.name} ({peak_i.op})",
        categories_at_peak=dict(cats),
        series=series,
        buffers=buffers,
        n_instructions=n,
        argument_bytes=sum(param_bytes.values()),
        output_bytes=root_instr.out_bytes,
        alias_bytes=alias_bytes,
        max_working_set_bytes=max_ws,
        output_signatures=out_sigs,
    )

"""ATX2xx — buffer-donation rules.

A train step that doesn't donate its state holds old + new params, moments,
and loss-scale simultaneously: 2x the state's HBM at peak, which on a
budgeted pod run is the difference between fitting and OOM. Donation is
visible statically: jax lowers it to ``tf.aliasing_output`` attributes on
the StableHLO entry args, and reports donations XLA had to drop (dtype or
layout mismatch with every output) as a lowering-time warning.
"""

from __future__ import annotations

import re
from collections import Counter
from typing import Any, Iterator

import jax
import numpy as np

from .engine import LintContext, _leaf_bytes, rule
from .findings import Finding, Severity
from .hbm import human_bytes

_ALIAS_MARKER = "tf.aliasing_output"
_DROPPED_MARKER = "donated buffers were not usable"
# With sharded inputs jax defers donation to XLA compile; the compiled
# module header then carries `input_output_alias={ {0}: (0, {}, may-alias) }`.
_COMPILED_ALIAS_RE = re.compile(r"input_output_alias=\{\s*\{")


def _donation_active(ctx: LintContext) -> bool:
    lowered_text = ctx.lowered_text()
    if lowered_text is not None and _ALIAS_MARKER in lowered_text:
        return True
    compiled_text = ctx.compiled_text()
    return compiled_text is not None and bool(_COMPILED_ALIAS_RE.search(compiled_text))


def _leaf_signature_counts(tree: Any) -> Counter:
    """Multiset of (shape, dtype) over array-like leaves."""
    counts: Counter = Counter()
    for leaf in jax.tree.leaves(tree):
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None or dtype is None:
            continue
        counts[(tuple(shape), np.dtype(dtype).str)] += 1
    return counts


@rule(
    "ATX201",
    Severity.WARNING,
    "donation",
    "large step input not donated although outputs could reuse its buffers",
    "donate the state argument (jit donate_argnums / "
    "make_train_step(donate=True)) and don't touch the old state after "
    "the call",
    needs={"fn"},
)
def atx201_missing_donation(ctx: LintContext) -> Iterator[Finding]:
    if ctx.lowered_text() is None:
        return
    if _donation_active(ctx):
        # Donation is active (regardless of how the caller configured it —
        # a pre-jitted step bakes its own donate_argnums).
        return
    out = ctx.out_shapes()
    if out is None:
        return
    out_counts = _leaf_signature_counts(out)
    threshold = ctx.opt("donation_bytes_threshold")
    for i, arg in enumerate(ctx.args):
        if i in ctx.static_argnums:
            continue
        arg_counts = _leaf_signature_counts(arg)
        reusable = sum(
            min(n, out_counts[sig])
            * int(np.prod(sig[0], dtype=np.int64))
            * np.dtype(sig[1]).itemsize
            for sig, n in arg_counts.items()
            if sig in out_counts
        )
        if reusable >= threshold:
            arg_total = sum(
                _leaf_bytes(l)
                for l in jax.tree.leaves(arg)
                if hasattr(l, "shape") and hasattr(l, "dtype")
            )
            yield Finding(
                "ATX201",
                Severity.WARNING,
                f"args[{i}]",
                f"{human_bytes(reusable)} of the outputs match this "
                f"argument's buffers ({human_bytes(arg_total)} total) but "
                "the argument is not donated — XLA allocates fresh output "
                "buffers, ~2x transient HBM for the train state",
                f"pass donate_argnums=({i},) (the Accelerator's "
                "make_train_step donates the state by default) and don't "
                "reuse the old value after the call",
            )


@rule(
    "ATX202",
    Severity.WARNING,
    "donation",
    "donation declared but dropped by XLA (no output can alias the buffer)",
    "donated buffers must match an output's shape/dtype — check dtype "
    "casts on the returned state and outputs whose sharding differs from "
    "the input's",
    needs={"fn"},
)
def atx202_dropped_donation(ctx: LintContext) -> Iterator[Finding]:
    if ctx.lowered() is None:
        return
    compiled_text = ctx.compiled_text()  # sharded-arg donation resolves here
    fix = (
        "make the returned state keep the donated leaves' exact "
        "dtype/shape (a cast like fp32->bf16 on the way out breaks "
        "aliasing), or stop donating args that don't round-trip"
    )
    reported = False
    for w in ctx.lowering_warnings:
        msg = str(w.message)
        if _DROPPED_MARKER in msg.lower():
            reported = True
            detail = msg.split(":", 1)[-1].strip().split("\n")[0]
            yield Finding(
                "ATX202",
                Severity.WARNING,
                "",
                "donation declared but XLA could not alias the donated "
                f"buffer(s) to any output — donation dropped for: {detail}. "
                "The old buffer stays live, so the donation saves nothing",
                fix,
            )
    # jax drops donations of SHARDED args silently (no warning on 0.4.x):
    # donation was declared, the module compiled, and yet no input-output
    # alias exists anywhere — the 2x-HBM saving the caller thinks they have
    # is not there.
    if (
        not reported
        and ctx.donate_argnums
        and compiled_text is not None
        and not _donation_active(ctx)
    ):
        yield Finding(
            "ATX202",
            Severity.WARNING,
            f"args{list(ctx.donate_argnums)}",
            "donation declared for these args but the compiled module has "
            "no input-output alias — XLA dropped every donation silently; "
            "old and new buffers coexist (~2x state HBM)",
            fix,
        )

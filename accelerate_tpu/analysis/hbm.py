"""Per-device HBM accounting from shapes + PartitionSpecs (no execution).

The same arithmetic GSPMD applies: a leaf's per-device footprint is its
byte size divided by the product of the mesh-axis sizes its spec names,
with indivisible dims rounded up (XLA pads the ragged shard). Used by the
`atx lint` CLI summary and cross-checked against `commands/estimate.py`'s
heuristic calculator in tests (they must agree within 5% on the shared
terms — params, grads, optimizer moments).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any

import jax
import numpy as np

from ..parallel.mesh import spec_entry_axes


def human_bytes(n: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024:
            return f"{n:.2f} {unit}"
        n /= 1024
    return f"{n:.2f} PiB"


def leaf_device_bytes(shape: tuple[int, ...], dtype: Any, spec: Any, mesh: Any) -> int:
    """Bytes one device holds for a leaf under ``spec`` (ceil per sharded
    dim — the padded-shard size XLA actually allocates)."""
    per_dim = list(shape)
    for d, entry in enumerate(spec or ()):
        if d >= len(per_dim):
            break
        group = 1
        for axis in spec_entry_axes(entry):
            group *= int(mesh.shape[axis])
        if group > 1:
            per_dim[d] = math.ceil(per_dim[d] / group)
    return int(np.prod(per_dim, dtype=np.int64)) * np.dtype(dtype).itemsize


def tree_device_bytes(shapes: Any, specs: Any, mesh: Any, dtype: Any | None = None) -> int:
    """Summed per-device bytes for a shapes pytree under a specs pytree.
    ``dtype`` overrides every leaf's dtype (e.g. fp32 for gradients)."""
    from jax.sharding import PartitionSpec

    shape_leaves = jax.tree.leaves(shapes)
    spec_leaves = jax.tree.leaves(
        specs, is_leaf=lambda x: isinstance(x, PartitionSpec)
    )
    if len(shape_leaves) != len(spec_leaves):
        raise ValueError(
            f"shapes tree has {len(shape_leaves)} leaves but specs tree has "
            f"{len(spec_leaves)}; the trees must mirror each other"
        )
    return sum(
        leaf_device_bytes(
            tuple(leaf.shape), dtype if dtype is not None else leaf.dtype, spec, mesh
        )
        for leaf, spec in zip(shape_leaves, spec_leaves)
    )


@dataclass(frozen=True)
class HbmBreakdown:
    """Per-device steady-state training footprint of the sharded state."""

    params_bytes: int
    grads_bytes: int
    opt_bytes: int

    @property
    def total(self) -> int:
        return self.params_bytes + self.grads_bytes + self.opt_bytes

    def format(self) -> str:
        return (
            f"params {human_bytes(self.params_bytes)} + "
            f"grads {human_bytes(self.grads_bytes)} + "
            f"opt {human_bytes(self.opt_bytes)} = "
            f"{human_bytes(self.total)}/device (state only; activations "
            "and logits are workload-dependent — see `atx estimate`)"
        )


def state_hbm_per_device(
    params_shapes: Any,
    param_specs: Any,
    mesh: Any,
    *,
    opt_shapes: Any = None,
    opt_specs: Any = None,
    include_grads: bool = True,
) -> HbmBreakdown:
    """Account the train state's per-device HBM: params at their own dtype,
    gradients as fp32 copies sharded like their params (what the compiled
    step materializes), optimizer state under its own specs."""
    import jax.numpy as jnp

    params_b = tree_device_bytes(params_shapes, param_specs, mesh)
    grads_b = (
        tree_device_bytes(params_shapes, param_specs, mesh, dtype=jnp.float32)
        if include_grads
        else 0
    )
    opt_b = 0
    if opt_shapes is not None and opt_specs is not None:
        opt_b = tree_device_bytes(opt_shapes, opt_specs, mesh)
    return HbmBreakdown(params_b, grads_b, opt_b)

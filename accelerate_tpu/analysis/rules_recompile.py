"""ATX3xx — recompilation-hazard rules.

A recompile mid-run stalls every chip in the slice for the full XLA
compile time (minutes at pod scale). The triggers are all visible in the
call signature: static args that aren't stable hashables, batch shapes
that drift call-to-call (the classic `drop_last=False` ragged tail), and
dtype/weak-type flips from mixing Python scalars with arrays.
"""

from __future__ import annotations

from typing import Any, Iterator

import jax
import numpy as np

from .engine import LintContext, rule
from ..parallel.sharding import _path_str
from .findings import Finding, Severity


def _leaf_sigs(args: Any, static_argnums: tuple[int, ...]) -> list[tuple[str, tuple, str, bool]]:
    """(path, shape, dtype, weak_type) per traced leaf, argv-prefixed."""
    sigs = []
    for i, arg in enumerate(args):
        if i in static_argnums:
            continue
        flat, _ = jax.tree_util.tree_flatten_with_path(arg)
        for p, leaf in flat:
            shape = getattr(leaf, "shape", None)
            dtype = getattr(leaf, "dtype", None)
            if shape is None or dtype is None:
                continue
            sigs.append(
                (
                    f"args[{i}]/{_path_str(p)}" if p else f"args[{i}]",
                    tuple(shape),
                    np.dtype(dtype).str,
                    bool(getattr(leaf, "weak_type", False)),
                )
            )
    return sigs


@rule(
    "ATX301",
    Severity.ERROR,
    "recompilation",
    "static argument is unhashable (or recompiles per distinct value)",
    "make the value a traced argument, or pass a hashable frozen form "
    "(tuple / frozen dataclass) that is constant across the run",
    needs={"fn"},
)
def atx301_static_args(ctx: LintContext) -> Iterator[Finding]:
    for i in ctx.static_argnums:
        if i >= len(ctx.args):
            continue
        value = ctx.args[i]
        try:
            hash(value)
        except TypeError:
            yield Finding(
                "ATX301",
                Severity.ERROR,
                f"args[{i}]",
                f"static argument of type {type(value).__name__} is "
                "unhashable — jit raises at call time (and a mutable "
                "static can never cache correctly)",
                "pass it as a traced argument, or freeze it "
                "(tuple / frozen dataclass) if it is genuinely static",
            )
            continue
        if isinstance(value, float) and not isinstance(value, bool):
            yield Finding(
                "ATX301",
                Severity.INFO,
                f"args[{i}]",
                f"float static argument ({value!r}) retraces and recompiles "
                "for every distinct value — a schedule or loss scale passed "
                "statically compiles once per step",
                "pass per-step scalars as traced args (or fold schedules "
                "into the optax chain)",
            )


@rule(
    "ATX302",
    Severity.WARNING,
    "recompilation",
    "argument shapes differ across the provided sample calls",
    "pad/bucket inputs to fixed shapes, or set drop_last=True so the "
    "ragged final batch never reaches the compiled step",
    needs={"fn"},
)
def atx302_shape_drift(ctx: LintContext) -> Iterator[Finding]:
    base = _leaf_sigs(ctx.args, ctx.static_argnums)
    for j, alt in enumerate(ctx.alternates):
        alt_sigs = _leaf_sigs(alt, ctx.static_argnums)
        if [s[0] for s in alt_sigs] != [s[0] for s in base]:
            yield Finding(
                "ATX302",
                Severity.WARNING,
                f"alternates[{j}]",
                "pytree structure differs from the primary call signature — "
                "every distinct structure compiles its own executable",
                "keep the batch pytree structure fixed across steps",
            )
            continue
        for (path, shape, _, _), (_, alt_shape, _, _) in zip(base, alt_sigs):
            if shape != alt_shape:
                yield Finding(
                    "ATX302",
                    Severity.WARNING,
                    path,
                    f"shape drifts across calls ({shape} vs {alt_shape}) — "
                    "each distinct shape triggers a full XLA recompile "
                    "that stalls every chip in the slice",
                    "pad/bucket to fixed shapes, or drop_last=True on the "
                    "loader so the ragged tail batch never compiles",
                )


@rule(
    "ATX303",
    Severity.WARNING,
    "recompilation",
    "dtype / weak-type flips across the provided sample calls",
    "canonicalize dtypes at the data boundary (np.asarray(..., dtype=...)); "
    "never alternate Python scalars with arrays for the same argument",
    needs={"fn"},
)
def atx303_dtype_drift(ctx: LintContext) -> Iterator[Finding]:
    base = _leaf_sigs(ctx.args, ctx.static_argnums)
    for j, alt in enumerate(ctx.alternates):
        alt_sigs = _leaf_sigs(alt, ctx.static_argnums)
        if [s[0] for s in alt_sigs] != [s[0] for s in base]:
            continue  # structure drift is ATX302's finding
        for (path, shape, dtype, weak), (_, alt_shape, alt_dtype, alt_weak) in zip(
            base, alt_sigs
        ):
            if shape != alt_shape:
                continue  # shape drift is ATX302's finding
            if dtype != alt_dtype:
                yield Finding(
                    "ATX303",
                    Severity.WARNING,
                    path,
                    f"dtype drifts across calls ({dtype} vs {alt_dtype}) — "
                    "a silent recompile per dtype (and x64 inputs are "
                    "silently downcast when jax_enable_x64 is off)",
                    "canonicalize dtypes where data enters the step "
                    "(np.asarray(..., dtype=np.float32))",
                )
            elif weak != alt_weak:
                yield Finding(
                    "ATX303",
                    Severity.WARNING,
                    path,
                    "weak-type flips across calls (Python scalar vs array) "
                    "— weak_type is part of jit's cache key, so the flip "
                    "recompiles and can change promotion semantics",
                    "pass the value with an explicit dtype "
                    "(jnp.asarray(x, jnp.float32)) on every call",
                )

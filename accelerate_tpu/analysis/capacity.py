"""Serving capacity planner: how many KV slots (or paged KV blocks)
statically fit beside the weights on one chip.

The serving engine's HBM footprint is fully determined before anything
runs: weights + the slot KV pool (`init_cache_fn(slots, max_len)`) + the
prefix-cache pool + peak decode activations. Given a `ChipSpec` (or an
explicit HBM budget) this module solves the only free variable — the slot
count — ahead of time, so "what occupancy can this chip sustain" and
"will engine init OOM" are planner arithmetic instead of run-and-see:

- `plan_capacity(...)` — pure arithmetic over byte counts; also answers
  the paged-KV form (`max_blocks(block_size)`): with rows allocated in
  ``block_size``-token pages, occupancy is bounded by *tokens*, not
  slots — the ROADMAP's vLLM-PagedAttention direction.
- `plan_for_engine(engine)` — reads the byte counts off a constructed
  `serving.Engine` (weights from ``engine.params``, per-slot bytes from
  the committed pool, prefix pool as overhead).
- `capacity_findings(...)` — the planner as ATX706 findings for the
  `atx lint serving` scenario (ERROR when the configured engine cannot
  fit, INFO otherwise; `serve_static_max_slots` rides in `Finding.data`
  for the `perf/budgets.json` ratchet). ATX706 is emitted by the serving
  scenario in `commands/lint.py` — not rule-registered, because it needs
  a constructed engine, not a step function.
- `check_engine_capacity(engine)` — the `Engine.__init__` guard behind
  ``ATX_SERVE_CAPACITY_CHECK`` (default "warn"; "error" raises the
  structured `CapacityError` with the max-slots suggestion; "0"/"off"
  skips). ``ATX_SERVE_CAPACITY_HBM_MIB`` overrides the HBM budget so
  tests seed an over-capacity config without allocating anything.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any

from .findings import Finding, Severity
from .hbm import human_bytes
from .roofline import ChipSpec, chip_spec_for

__all__ = [
    "CapacityError",
    "CapacityPlan",
    "capacity_findings",
    "check_engine_capacity",
    "plan_capacity",
    "plan_for_engine",
    "tree_bytes",
]


def tree_bytes(tree: Any) -> int:
    """Total bytes of a pytree of arrays / ShapeDtypeStructs."""
    import jax
    import numpy as np

    total = 0
    for leaf in jax.tree.leaves(tree):
        shape = getattr(leaf, "shape", ())
        dtype = getattr(leaf, "dtype", None)
        itemsize = np.dtype(dtype).itemsize if dtype is not None else 0
        total += int(math.prod(shape)) * itemsize
    return total


@dataclasses.dataclass(frozen=True)
class CapacityPlan:
    """Static HBM plan for one serving engine on one chip."""

    chip: str
    hbm_bytes: int            # budget being planned against
    weights_bytes: int
    kv_bytes_per_slot: int    # one slot row across all layers, max_len tokens
    kv_bytes_per_token: int   # one KV position across all layers
    act_peak_bytes: int       # peak decode activations (0 when unknown)
    overhead_bytes: int       # prefix-cache pool + other fixed allocations
    n_slots: int              # the configured slot count being judged
    max_len: int

    @property
    def kv_pool_bytes(self) -> int:
        return self.kv_bytes_per_slot * self.n_slots

    @property
    def static_total_bytes(self) -> int:
        """Footprint of the configured engine: weights + slot pool +
        overhead + peak decode activations."""
        return (
            self.weights_bytes + self.kv_pool_bytes + self.overhead_bytes
            + self.act_peak_bytes
        )

    @property
    def free_bytes(self) -> int:
        """HBM left for KV after everything that is not the slot pool."""
        return self.hbm_bytes - self.weights_bytes - self.overhead_bytes - self.act_peak_bytes

    @property
    def max_slots(self) -> int:
        """Largest slot count that statically fits this chip."""
        if self.kv_bytes_per_slot <= 0:
            return 0
        return max(self.free_bytes // self.kv_bytes_per_slot, 0)

    @property
    def fits(self) -> bool:
        return self.static_total_bytes <= self.hbm_bytes

    def max_blocks(self, block_size: int) -> int:
        """Paged-KV form: max ``block_size``-token pages that fit in the
        same free bytes — occupancy bounded by tokens, not slots."""
        block_bytes = self.kv_bytes_per_token * max(block_size, 1)
        if block_bytes <= 0:
            return 0
        return max(self.free_bytes // block_bytes, 0)

    def format(self) -> str:
        verdict = (
            f"fits ({human_bytes(self.hbm_bytes - self.static_total_bytes)} headroom)"
            if self.fits
            else f"DOES NOT FIT (over by {human_bytes(self.static_total_bytes - self.hbm_bytes)})"
        )
        return (
            f"capacity[{self.chip}]: weights {human_bytes(self.weights_bytes)}"
            f" + kv {self.n_slots}x{human_bytes(self.kv_bytes_per_slot)}/slot"
            f" (max_len {self.max_len})"
            f" + overhead {human_bytes(self.overhead_bytes)}"
            f" + activations {human_bytes(self.act_peak_bytes)}"
            f" = {human_bytes(self.static_total_bytes)}"
            f" of {human_bytes(self.hbm_bytes)} — {verdict};"
            f" static max slots {self.max_slots}"
        )


class CapacityError(RuntimeError):
    """Engine config statically cannot fit its chip. Carries the plan
    (``err.plan``) so callers can read the max-slots suggestion."""

    def __init__(self, plan: CapacityPlan):
        self.plan = plan
        super().__init__(
            f"{plan.format()} — lower slots to <= {plan.max_slots}, shrink "
            f"max_len, or quantize the KV cache (ATX_SERVE_CAPACITY_CHECK=0 "
            f"to bypass)"
        )


def plan_capacity(
    *,
    chip: "str | ChipSpec | None" = None,
    hbm_bytes: int | None = None,
    weights_bytes: int,
    kv_bytes_per_slot: int,
    n_slots: int,
    max_len: int,
    act_peak_bytes: int = 0,
    overhead_bytes: int = 0,
) -> CapacityPlan:
    """Pure-arithmetic capacity plan. ``hbm_bytes`` overrides the chip's
    HBM (tests; explicit budgets); ``kv_bytes_per_token`` is derived as
    per-slot bytes / max_len."""
    spec = chip if isinstance(chip, ChipSpec) else chip_spec_for(chip)
    return CapacityPlan(
        chip=spec.name,
        hbm_bytes=int(hbm_bytes if hbm_bytes is not None else spec.hbm_bytes),
        weights_bytes=int(weights_bytes),
        kv_bytes_per_slot=int(kv_bytes_per_slot),
        kv_bytes_per_token=int(kv_bytes_per_slot) // max(int(max_len), 1),
        act_peak_bytes=int(act_peak_bytes),
        overhead_bytes=int(overhead_bytes),
        n_slots=int(n_slots),
        max_len=int(max_len),
    )


def plan_for_engine(
    engine: Any,
    *,
    chip: "str | ChipSpec | None" = None,
    hbm_bytes: int | None = None,
    act_peak_bytes: int = 0,
) -> CapacityPlan:
    """Plan for a constructed `serving.Engine`: weights from its params,
    per-slot KV from the committed slot pool, the prefix-cache pool as
    fixed overhead."""
    kv_pool = tree_bytes(engine._kv)
    return plan_capacity(
        chip=chip,
        hbm_bytes=hbm_bytes,
        weights_bytes=tree_bytes(engine.params),
        kv_bytes_per_slot=kv_pool // max(engine.n_slots, 1),
        n_slots=engine.n_slots,
        max_len=engine.max_len,
        act_peak_bytes=act_peak_bytes,
        overhead_bytes=tree_bytes(engine._pool) if engine._pool is not None else 0,
    )


def capacity_findings(
    engine: Any,
    *,
    chip: "str | ChipSpec | None" = None,
    hbm_bytes: int | None = None,
    act_peak_bytes: int = 0,
    block_size: int = 16,
) -> list[Finding]:
    """The planner as ATX706 findings (the `atx lint serving` surface)."""
    plan = plan_for_engine(
        engine, chip=chip, hbm_bytes=hbm_bytes, act_peak_bytes=act_peak_bytes
    )
    severity = Severity.INFO if plan.fits else Severity.ERROR
    message = plan.format()
    if not plan.fits:
        message += (
            f" — engine init would OOM on {plan.chip}; lower slots to "
            f"<= {plan.max_slots} or shrink max_len"
        )
    return [
        Finding(
            "ATX706",
            severity,
            plan.chip,
            message,
            "" if plan.fits else (
                "the slot KV pool is allocated in one piece at engine init "
                "— size it with the planner (atx estimate --serve) instead "
                "of discovering the OOM on the pod"
            ),
            data={
                "chip": plan.chip,
                "hbm_bytes": plan.hbm_bytes,
                "weights_bytes": plan.weights_bytes,
                "kv_bytes_per_slot": plan.kv_bytes_per_slot,
                "kv_bytes_per_token": plan.kv_bytes_per_token,
                "overhead_bytes": plan.overhead_bytes,
                "act_peak_bytes": plan.act_peak_bytes,
                "n_slots": plan.n_slots,
                "max_len": plan.max_len,
                "static_total_bytes": plan.static_total_bytes,
                "fits": plan.fits,
                "serve_static_max_slots": plan.max_slots,
                "max_blocks": {
                    str(block_size): plan.max_blocks(block_size),
                },
            },
        )
    ]


def check_engine_capacity(engine: Any) -> "CapacityPlan | None":
    """`Engine.__init__` guard. ``ATX_SERVE_CAPACITY_CHECK`` picks the
    mode: "warn" (default) warns on a statically-unfitting config,
    "error" raises `CapacityError`, "0"/"off"/"false"/"none" skips.
    ``ATX_SERVE_CAPACITY_HBM_MIB`` overrides the HBM budget (the local
    chip's spec otherwise). Returns the plan (None when skipped)."""
    import warnings

    from ..utils.environment import get_int_from_env, get_str_from_env

    mode = get_str_from_env(("ATX_SERVE_CAPACITY_CHECK",), "warn").strip().lower()
    if mode in ("0", "off", "false", "none", "no"):
        return None
    hbm_mib = get_int_from_env(("ATX_SERVE_CAPACITY_HBM_MIB",), 0)
    plan = plan_for_engine(
        engine, hbm_bytes=hbm_mib << 20 if hbm_mib > 0 else None
    )
    if not plan.fits:
        if mode == "error":
            raise CapacityError(plan)
        warnings.warn(
            f"serving engine statically exceeds {plan.chip} HBM: "
            f"{plan.format()} (set ATX_SERVE_CAPACITY_CHECK=error to fail "
            f"fast, =0 to silence)",
            RuntimeWarning,
            stacklevel=3,
        )
    return plan

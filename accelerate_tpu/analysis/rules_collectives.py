"""ATX4xx — host-sync and collective-traffic rules.

Two failure classes the jaxpr and the compiled HLO expose statically:

- host round-trips inside the hot step (`pure_callback`/`io_callback`/
  `jax.debug.print`): each one fences the device stream and syncs
  device->host every step;
- collective traffic GSPMD inserted: the optimized HLO names every
  all-gather/all-reduce with its result shape, so the bytes each step
  moves over ICI are countable ahead of time — and a single all-gather
  whose output approaches the full parameter byte count is the signature
  of an accidental replication (a spec typo turned FSDP into "gather
  everything, everywhere, every step").
"""

from __future__ import annotations

import dataclasses
import re
from collections import defaultdict
from typing import Any, Iterator

import jax
import numpy as np

from .engine import LintContext, _leaf_bytes, rule
from .findings import Finding, Severity
from .hbm import human_bytes

_CALLBACK_PRIMS = {"pure_callback", "io_callback"}
_DEBUG_PRIMS = {"debug_callback"}

_HLO_DTYPE_BYTES = {
    "pred": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16, "f8e4m3fn": 1, "f8e5m2": 1,
}

# `%name = f32[16,512]{1,0} all-reduce(...)` — or a tuple result
# `(f32[8,4]{1,0}, f32[8,4]{1,0}) all-reduce(...)`; async variants lower
# to `-start`/`-done` pairs (byte totals count the start, skip the done;
# ATX602 matches the pairs up by position to judge overlap).
_COLLECTIVE_RE = re.compile(
    r"%(?P<name>[\w.\-]+)\s*"
    r"=\s+(?P<shape>\([^)]*\)|[a-z0-9]+\[[0-9,]*\](?:\{[^}]*\})?)\s+"
    r"(?P<op>all-gather|all-reduce|reduce-scatter|all-to-all|collective-permute)"
    r"(?P<variant>-start|-done)?\("
)
_SHAPE_RE = re.compile(r"([a-z0-9]+)\[([0-9,]*)\]")


def _shape_bytes(text: str) -> int:
    """Bytes of one HLO result shape (sums tuple elements)."""
    total = 0
    for dtype, dims in _SHAPE_RE.findall(text):
        nbytes = _HLO_DTYPE_BYTES.get(dtype)
        if nbytes is None:
            continue
        count = 1
        for d in dims.split(","):
            if d:
                count *= int(d)
        total += count * nbytes
    return total


@dataclasses.dataclass(frozen=True)
class CollectiveSite:
    """One collective instruction located in the HLO text. ``variant`` is
    "sync", "start", or "done"; ``line`` is the 0-based text line, so the
    ATX602 overlap rule can measure what sits between a start/done pair."""

    op: str
    variant: str
    name: str
    bytes: int
    line: int


def parse_collectives_detailed(hlo_text: str) -> list[CollectiveSite]:
    """Every collective site in optimized HLO text, in program order, with
    async `-start`/`-done` variants distinguished and positioned."""
    sites = []
    for line_no, line in enumerate(hlo_text.splitlines()):
        for m in _COLLECTIVE_RE.finditer(line):
            variant = (m.group("variant") or "-sync").lstrip("-")
            sites.append(
                CollectiveSite(
                    op=m.group("op"),
                    variant=variant,
                    name=m.group("name"),
                    bytes=_shape_bytes(m.group("shape")),
                    line=line_no,
                )
            )
    return sites


def parse_collectives(hlo_text: str) -> list[tuple[str, int]]:
    """(op, result_bytes) per collective in optimized HLO text. Result
    shapes are per-device (post-partitioning), i.e. what each chip
    materializes for the op. `-done` halves of async pairs are skipped —
    the `-start` already carried the bytes."""
    return [
        (s.op, s.bytes)
        for s in parse_collectives_detailed(hlo_text)
        if s.variant != "done"
    ]


def _iter_eqns(jaxpr: Any) -> Iterator[Any]:
    """All eqns in a jaxpr, recursing into sub-jaxprs (pjit bodies, scan,
    cond branches, custom_* calls)."""
    for eqn in jaxpr.eqns:
        yield eqn
        for value in eqn.params.values():
            for sub in _sub_jaxprs(value):
                yield from _iter_eqns(sub)


def _sub_jaxprs(value: Any) -> Iterator[Any]:
    if hasattr(value, "jaxpr"):  # ClosedJaxpr
        yield value.jaxpr
    elif hasattr(value, "eqns"):  # raw Jaxpr
        yield value
    elif isinstance(value, (tuple, list)):
        for v in value:
            yield from _sub_jaxprs(v)


@rule(
    "ATX401",
    Severity.WARNING,
    "host-sync",
    "host callback inside the hot jaxpr (device->host sync every step)",
    "move the host work outside the compiled step, or batch it behind an "
    "explicit metrics fetch every N steps",
    needs={"fn"},
)
def atx401_callbacks(ctx: LintContext) -> Iterator[Finding]:
    closed = ctx.jaxpr()
    if closed is None:
        return
    counts: dict[str, int] = defaultdict(int)
    for eqn in _iter_eqns(closed.jaxpr):
        if eqn.primitive.name in _CALLBACK_PRIMS:
            counts[eqn.primitive.name] += 1
    for name, n in sorted(counts.items()):
        yield Finding(
            "ATX401",
            Severity.WARNING,
            name,
            f"{n} `{name}` call(s) traced into the step — each one fences "
            "the device stream and round-trips device->host every step, "
            "serializing dispatch on TPU",
            "hoist the host work out of the jitted step (act on the "
            "returned metrics instead), or amortize it every N steps",
        )


@rule(
    "ATX402",
    Severity.WARNING,
    "host-sync",
    "jax.debug.print / debug callback left in the hot jaxpr",
    "remove it or gate it behind a debug flag; it syncs device->host on "
    "every step",
    needs={"fn"},
)
def atx402_debug_print(ctx: LintContext) -> Iterator[Finding]:
    closed = ctx.jaxpr()
    if closed is None:
        return
    n = sum(
        1 for eqn in _iter_eqns(closed.jaxpr) if eqn.primitive.name in _DEBUG_PRIMS
    )
    if n:
        yield Finding(
            "ATX402",
            Severity.WARNING,
            "debug_callback",
            f"{n} jax.debug.print/breakpoint call(s) traced into the step — "
            "fine for debugging, a per-step host sync in production",
            "delete it, or gate it behind a flag that is False when "
            "compiling the production step",
        )


def _total_param_bytes(ctx: LintContext) -> int:
    if ctx.params_shapes is None:
        return 0
    return sum(
        _leaf_bytes(l)
        for l in jax.tree.leaves(ctx.params_shapes)
        if hasattr(l, "shape") and hasattr(l, "dtype")
    )


@rule(
    "ATX403",
    Severity.WARNING,
    "collectives",
    "single all-gather moves a full-parameter-scale buffer every step",
    "a gather this size usually means a spec typo replicated something "
    "that was meant to stay sharded — check the output constraints and "
    "the param specs feeding this step",
    needs={"fn"},
)
def atx403_giant_gather(ctx: LintContext) -> Iterator[Finding]:
    hlo = ctx.compiled_text()
    if hlo is None:
        return
    param_total = _total_param_bytes(ctx)
    abs_threshold = ctx.opt("gather_bytes_threshold")
    frac = ctx.opt("gather_param_fraction")
    min_bytes = ctx.opt("gather_min_bytes")
    for op, nbytes in parse_collectives(hlo):
        if op != "all-gather":
            continue
        relative_hit = (
            param_total > 0 and nbytes >= frac * param_total and nbytes >= min_bytes
        )
        if nbytes >= abs_threshold or relative_hit:
            detail = (
                f" ({100 * nbytes / param_total:.0f}% of the "
                f"{human_bytes(param_total)} total param bytes)"
                if param_total
                else ""
            )
            yield Finding(
                "ATX403",
                Severity.WARNING,
                "all-gather",
                f"a single all-gather materializes {human_bytes(nbytes)} "
                f"per device per step{detail} — the accidental-replication "
                "signature (a wrong spec makes XLA gather instead of "
                "erroring, 5-50x slower)",
                "find the op's source in the compiled HLO metadata; the "
                "usual causes are an output sharding constraint of P() on "
                "sharded state, or a spec axis dropped by ATX101/ATX102",
            )


@rule(
    "ATX404",
    Severity.INFO,
    "collectives",
    "per-step collective traffic summary mined from the compiled HLO",
    "",
    needs={"fn"},
)
def atx404_traffic_summary(ctx: LintContext) -> Iterator[Finding]:
    hlo = ctx.compiled_text()
    if hlo is None:
        return
    totals: dict[str, tuple[int, int]] = {}
    for op, nbytes in parse_collectives(hlo):
        count, acc = totals.get(op, (0, 0))
        totals[op] = (count + 1, acc + nbytes)
    if not totals:
        return
    parts = [
        f"{op} x{count} ({human_bytes(nbytes)})"
        for op, (count, nbytes) in sorted(totals.items())
    ]
    yield Finding(
        "ATX404",
        Severity.INFO,
        "",
        "collective traffic per step (per-device result bytes): "
        + ", ".join(parts),
        "",
        data={
            "collectives": [
                {"op": op, "count": count, "bytes": nbytes}
                for op, (count, nbytes) in sorted(totals.items())
            ]
        },
    )

# Test lanes (the reference splits CI the same way, Makefile:25-60).
#
#   make test        - fast lane: skips tests marked `heavy` (< ~5 min)
#   make test-heavy  - ONLY the heavy lane (compile-heavy, subprocess launches)
#   make test-all    - everything
#
# The heavy marker lives on whole files (attention kernels, model-zoo
# forward parity, HF interop, HLO verification, examples, CLI/multiprocess
# launches, checkpointing); `pytest tests/ --heavy` is the raw invocation.

.PHONY: test test-heavy test-all smoke-transfer smoke-serve smoke-router smoke-resilience smoke-replication smoke-elastic smoke-shrink smoke-kernels smoke-telemetry smoke-chaos smoke-trace lint-graph lint-multihost lint-perf lint-memory

test:
	python -m pytest tests/ -q

# Fast CPU smoke over the transfer-engine code paths (tiny arrays, no TPU):
# the engine unit tests plus the disk-offload overlap/sentinel integration.
smoke-transfer:
	JAX_PLATFORMS=cpu python -m pytest tests/test_transfer.py tests/test_disk_offload.py -q -m 'not slow'

# CPU smoke for the continuous-batching serving engine (docs/serving.md):
# tiny model, a 16-request Poisson trace that must fully complete with
# outputs bit-identical to solo generate, a shared-system-prompt trace
# that must show prefix_hit_rate > 0 with >= 50% of prompt tokens served
# from the radix prefix cache AND stay bit-identical to the cache-off
# engine (tests/test_serving.py, tests/test_prefix_cache.py), plus `atx
# lint` over the engine's real decode step and the prefix-copy kernel —
# error-severity findings fail the lane.
smoke-serve:
	JAX_PLATFORMS=cpu python -m pytest tests/test_serving.py tests/test_prefix_cache.py tests/test_generation.py -q -m 'not slow'
	JAX_PLATFORMS=cpu python -m accelerate_tpu.commands.cli lint serving --severity error

# CPU smoke for the multi-replica serving front-end (docs/serving.md,
# "Multi-replica routing & drain"): 2-replica greedy outputs bit-identical
# to a solo engine — including under an injected replica kill mid-decode
# and a wedge caught by the per-replica watchdog — plus visible
# queue-full rejects, deadline cancels mid-queue and mid-decode, and the
# SIGTERM drain -> exit 75 subprocess contract; then the router_drain
# host-loop replay under 2 simulated processes (error findings fail).
smoke-router:
	JAX_PLATFORMS=cpu python -m pytest tests/test_router.py -q -m 'not slow'
	JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
		python -m accelerate_tpu.commands.cli lint router_drain --multihost 2 \
		--severity error

# Ahead-of-time step lint over the examples/ entry points (no training, no
# weights): fails on any error-severity finding (docs/static_analysis.md).
# The 8 simulated host devices give the sharding/collective rules a real
# mesh to check against.
lint-graph:
	JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
		python -m accelerate_tpu.commands.cli lint examples --severity error

# Static performance lint + budget ratchet (ATX6xx, docs/performance.md
# "perf campaign"): the example train steps plus the bench-scale llama2b
# config are compiled abstractly, the roofline rules run at error
# severity, and the ATX601 series (static MFU bound, exposed-comms bytes,
# padding-waste fraction) are checked against the committed
# perf/budgets.json — any regression past tolerance fails the lane.
# Rated at v5e so the series are TPU-shaped even on the CPU container.
lint-perf:
	JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
		python -m accelerate_tpu.commands.cli lint perf --severity error \
		--chip v5e --budgets perf/budgets.json

# Static memory lint + budget ratchet (ATX7xx, docs/static_analysis.md):
# the perf scenarios plus the serving engine get the compiled-HLO HBM
# timeline (peak live bytes vs the chip's HBM — ATX702 fires on a static
# OOM) and the serving capacity planner (ATX706), with the peak_hbm_mib /
# serve_static_max_slots series ratcheted against perf/budgets.json.
# Rated at v5e so the series are TPU-shaped even on the CPU container.
lint-memory:
	JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
		python -m accelerate_tpu.commands.cli lint memory --severity error \
		--chip v5e --budgets perf/budgets.json

# Multi-host SPMD-consistency lint (ATX5xx, docs/static_analysis.md): the
# example train steps are re-traced under 2 simulated processes (divergent
# jitted collectives fail), and the host-side save / preemption-exit loops
# are replayed process-by-process so a collective-schedule divergence — the
# kind that hangs a real pod — fails here instead.
lint-multihost:
	JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
		python -m accelerate_tpu.commands.cli lint --multihost 2 \
		nlp_example lm_example cv_example save_path preemption_exit \
		--severity error

# CPU resilience lane (docs/fault_tolerance.md): fault-injected save/load
# roundtrips (truncate / bit-flip / kill-9 mid-save must never lose the last
# committed checkpoint), the SIGTERM-resume bit-identity subprocess smoke,
# and the hang-watchdog abort smoke.
smoke-resilience:
	JAX_PLATFORMS=cpu python -m pytest tests/test_resilience.py -q -m 'not slow'

# CPU replication lane (docs/fault_tolerance.md, "Checkpoint replication &
# remote restore"): LocalObjectStore round-trip (save -> background upload
# -> delete local root -> restore-from-remote, bit-identical), the
# fault-injection subset (kill -9 mid-upload resumes skipping completed
# parts; transient-error backoff bounded + jittered), then the
# replicated_save host-loop replay under 2 simulated processes proving
# replication adds NO collectives (error findings fail).
smoke-replication:
	JAX_PLATFORMS=cpu python -m pytest tests/test_replication.py -q -m 'not slow'
	JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
		python -m accelerate_tpu.commands.cli lint replicated_save --multihost 2 \
		--severity error

# CPU elastic-resume lane (docs/fault_tolerance.md, "Elastic resume &
# resharding restore"): reshard-on-restore round trips (save under an
# 8-device FSDP mesh, restore bit-identical under 4 and 2 — optimizer
# moments included), peer-shard fetch from the object store with manifest
# verification (corrupt bytes rejected, kill -9 mid-fetch leaves the
# checkpoint untouched), the peer-health watchdog, the ATX_NAN_GUARD
# skip/abort budget, and the 8-dev -> SIGTERM -> 4-dev resume subprocess
# acceptance; then the elastic_restore host-loop replay under 2 simulated
# processes proving the restore path adds NO collectives (error findings
# fail).
smoke-elastic:
	JAX_PLATFORMS=cpu python -m pytest tests/test_elastic.py -q -m 'not slow'
	JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
		python -m accelerate_tpu.commands.cli lint elastic_restore --multihost 2 \
		--severity error

# CPU shrink-in-place lane (docs/fault_tolerance.md, "Shrink/grow in
# place"): the live-resize acceptance — an 8-rank (simulated) run loses 2
# peers mid-training, survivors agree and reshard IN PLACE (no relaunch),
# and post-shrink losses + Adam moments + step match a never-interrupted
# 6-device reference; grow-back; kill -9 / agreement-timeout mid-shrink
# degrading to the exit-75 relaunch with the prior commit intact; ranged
# object-store reads; then the shrink host-loop replay under 2 simulated
# processes proving escalate -> agree -> reshard -> resume adds NO
# collectives (error findings fail).
smoke-shrink:
	JAX_PLATFORMS=cpu python -m pytest tests/test_shrink.py -q -m 'not slow'
	JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
		python -m accelerate_tpu.commands.cli lint shrink --multihost 2 \
		--severity error

# CPU kernel-tier lane (docs/performance.md, "Pallas kernel tier"):
# interpret-mode parity of every Pallas kernel against its exact fallback
# lowering (flash-decode attention incl. GQA/ragged cursors/int8 KV,
# int8/fp8 fused matmul fwd+bwd, fused AdamW), dispatch-knob resolution,
# and `atx lint kernels` over the kernel-enabled decode + train steps
# (error-severity ATX findings fail the lane).
smoke-kernels:
	JAX_PLATFORMS=cpu python -m pytest tests/test_kernels.py -q -m 'not slow'
	JAX_PLATFORMS=cpu python -m accelerate_tpu.commands.cli lint kernels --severity error

# CPU telemetry lane (docs/observability.md): registry/histogram/span unit
# tests incl. the zero-device-sync and bit-identity gates, a 16-request
# `atx serve --metrics-port` run scraped live mid-trace with the Prometheus
# text cross-checked against the JSON summary, and the telemetry host-loop
# replay under 2 simulated processes proving metrics + snapshot export add
# NO collectives (error findings fail).
smoke-telemetry:
	JAX_PLATFORMS=cpu python -m pytest tests/test_telemetry.py -q -m 'not slow'
	JAX_PLATFORMS=cpu python tests/scripts/serve_scrape.py
	JAX_PLATFORMS=cpu python -m accelerate_tpu.commands.cli lint telemetry --multihost 2 \
		--severity error

# CPU chaos lane (docs/fault_tolerance.md, "Chaos campaigns"): the
# FaultSchedule seed-replay + campaign-digest unit tests, a fixed-seed
# 12-episode inline campaign over router/engine/replication (exactly-once,
# bit-identity, drain, no-torn-commit — any violation exits 1), and the
# router_recovery host-loop replay under 2 simulated processes proving
# quarantine -> probe -> re-admit -> prefix migration adds NO collectives
# (error findings fail).
smoke-chaos:
	JAX_PLATFORMS=cpu python -m pytest tests/test_chaos.py -q -m 'not slow'
	JAX_PLATFORMS=cpu python -m accelerate_tpu.commands.cli chaos \
		--episodes 12 --seed 0 --no-subprocess-episodes
	JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
		python -m accelerate_tpu.commands.cli lint router_recovery --multihost 2 \
		--severity error

# CPU tracing lane (docs/observability.md, "Request tracing & the flight
# recorder"): flight-recorder ring / postmortem-bundle / bench --compare
# unit tests incl. the exactly-once-through-failover and SystemExit-flush
# subprocess gates, a 16-request Poisson trace served twice proving
# ATX_TRACE_REQUESTS=1 is bit-identical to =0 with `atx trace --check
# 0.05` passing on both the bundle and the live JSONL dir (phase spans
# must sum to each request's e2e within 5%), and the tracing host-loop
# replay under 2 simulated processes proving span recording + the bundle
# dump add NO collectives (error findings fail).
smoke-trace:
	JAX_PLATFORMS=cpu python -m pytest tests/test_trace.py -q -m 'not slow'
	JAX_PLATFORMS=cpu python tests/scripts/trace_smoke.py
	JAX_PLATFORMS=cpu python -m accelerate_tpu.commands.cli lint tracing --multihost 2 \
		--severity error

test-heavy:
	python -m pytest tests/ -q -m heavy

test-all: lint-graph lint-multihost lint-perf lint-memory smoke-serve smoke-router smoke-resilience smoke-replication smoke-elastic smoke-shrink smoke-kernels smoke-telemetry smoke-chaos smoke-trace
	python -m pytest tests/ -q --heavy
